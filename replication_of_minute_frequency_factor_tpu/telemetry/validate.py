"""Validate a written telemetry directory (or flight dump) against the
schema.

    python -m replication_of_minute_frequency_factor_tpu.telemetry.validate DIR
    python -m ...telemetry.validate flight_123_001_breaker_trip.jsonl

Directory mode checks the artifacts ``Telemetry.write`` produces:

* ``manifest.json`` — parseable, a supported schema version, config hash;
* ``metrics.jsonl`` — EVERY line validates via :func:`..sink.validate_record`;
* ``trace.json`` — parseable Chrome trace with a ``traceEvents`` list;
* every ``flight_*.jsonl`` — flight-recorder dumps (ISSUE 8): each
  must lead with a ``dump`` header record and every line must validate.

File mode (a ``.jsonl`` path) validates one flight dump standalone —
the check the breaker-trip acceptance gate and the ops-plane smoke run
on a freshly captured dump.

Prints a one-line JSON report and exits non-zero on any problem — this
is the check ``run_tests.sh`` runs after the synthetic-pipeline smoke.
"""

from __future__ import annotations

import glob
import json
import os
import sys
from typing import List, Optional

from .sink import SCHEMA_VERSION, validate_jsonl

#: manifest schema versions this validator accepts (old bundles stay
#: checkable; the envelope validator enforces per-record versioning)
ACCEPTED_SCHEMAS = tuple(range(1, SCHEMA_VERSION + 1))


def validate_dump(path: str) -> dict:
    """Validate one flight-recorder dump file: every line schema-valid,
    at least one record, and a ``dump`` header record present."""
    problems: List[str] = []
    kinds: dict = {}
    n_lines = 0
    try:
        for lineno, line_problems in validate_jsonl(path):
            n_lines += 1
            for p in line_problems:
                problems.append(f"{os.path.basename(path)}:{lineno}: {p}")
    except OSError as e:
        problems.append(f"{path}: {e}")
    if not problems:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    k = json.loads(line).get("kind")
                except json.JSONDecodeError:
                    continue
                kinds[k] = kinds.get(k, 0) + 1
        if n_lines == 0:
            problems.append(f"{os.path.basename(path)} is empty")
        elif not kinds.get("dump"):
            problems.append(f"{os.path.basename(path)} has no 'dump' "
                            "header record")
    return {"ok": not problems, "path": path, "jsonl_lines": n_lines,
            "kinds": kinds, "problems": problems}


def validate_dir(out_dir: str) -> dict:
    """Report dict: ``{"ok": bool, "problems": [...], ...counts}``."""
    problems: List[str] = []
    kinds: dict = {}

    mpath = os.path.join(out_dir, "manifest.json")
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
        if manifest.get("schema") not in ACCEPTED_SCHEMAS:
            problems.append(f"manifest schema={manifest.get('schema')!r}")
        if not isinstance(manifest.get("config_hash"), str) \
                or len(manifest["config_hash"]) != 64:
            problems.append("manifest config_hash missing/malformed")
    except (OSError, json.JSONDecodeError) as e:
        problems.append(f"manifest.json: {e}")

    jpath = os.path.join(out_dir, "metrics.jsonl")
    n_lines = 0
    try:
        for lineno, line_problems in validate_jsonl(jpath):
            n_lines += 1
            for p in line_problems:
                problems.append(f"metrics.jsonl:{lineno}: {p}")
        if n_lines == 0:
            problems.append("metrics.jsonl is empty")
        else:
            with open(jpath) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        k = json.loads(line).get("kind")
                    except json.JSONDecodeError:
                        continue
                    kinds[k] = kinds.get(k, 0) + 1
    except OSError as e:
        problems.append(f"metrics.jsonl: {e}")

    tpath = os.path.join(out_dir, "trace.json")
    try:
        with open(tpath) as fh:
            trace = json.load(fh)
        if not isinstance(trace.get("traceEvents"), list):
            problems.append("trace.json has no traceEvents list")
    except (OSError, json.JSONDecodeError) as e:
        problems.append(f"trace.json: {e}")

    flights = sorted(glob.glob(os.path.join(out_dir, "flight_*.jsonl")))
    for fpath in flights:
        report = validate_dump(fpath)
        problems.extend(report["problems"])

    return {"ok": not problems, "dir": out_dir, "jsonl_lines": n_lines,
            "kinds": kinds, "flight_dumps": len(flights),
            "problems": problems}


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if len(argv) != 1:
        print("usage: python -m replication_of_minute_frequency_factor_tpu"
              ".telemetry.validate DIR|DUMP.jsonl", file=sys.stderr)
        return 2
    target = argv[0]
    if os.path.isfile(target):
        report = validate_dump(target)
    else:
        report = validate_dir(target)
    print(json.dumps(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
