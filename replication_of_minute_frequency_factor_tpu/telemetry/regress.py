"""Bench-series regression gate.

``python -m replication_of_minute_frequency_factor_tpu.telemetry.regress
ROOT`` parses the banked ``BENCH_r*.json`` trajectory under ``ROOT``
(the round-end driver artifacts committed at the repo root), builds
per-metric baselines, and flags deviations with a stage-level diff of
where the time moved. The verdict prints as ONE machine-readable JSON
line so harnesses (``run_tests.sh``'s regress smoke,
``benchmarks/tpu_session.py``'s end-of-session gate) can embed it.

Series semantics (VERDICT r4 #3: series breaks are DECLARED, not
smeared):

* records group by ``(metric, methodology)``. A record carrying a new
  ``methodology`` value starts a fresh series — one record alone has no
  baseline and is never flagged, so a declared break stays quiet by
  construction.
* records predating the ``methodology`` field (r01–r04) are all the
  r1–r4 double-buffered stream loop (bench.py's own series history), so
  they join the declared ``r4_stream_v2`` series rather than forming a
  phantom "undeclared" one. This is the ONE inference the gate makes,
  and it is pinned here so it cannot drift.
* declared series to date: ``r4_stream_v2`` (legacy + stream),
  ``r5_resident_v1`` (first resident scan), ``r6_resident_v2`` /
  ``r6_stream_v3`` (fused rolling engine + donation),
  ``r7_resident_sharded_v1`` (mesh-native resident scan:
  tickers-sharded wire buffers, overlapped group ingest, sharded
  fetch — bench stamps it only when ``n_shards > 1`` actually
  resolved; single-device resident runs stay on ``r6_resident_v2``),
  ``r8_serve_v1`` (the serving layer, ``bench.py serve``: steady QPS
  of the resident FactorServer at the record's highest concurrency
  level is the ``value``, with per-level p50/p99/QPS under
  ``levels`` and the serving counters — exposure-cache hits,
  coalesced dispatches, compiles-during-load — under ``serve``; a
  new workload, so its records never smear onto the batch series),
  ``r9_stream_intraday_v1`` (the online intraday engine,
  ``bench.py stream``: bars/sec at the record's largest cohort
  ingest shape is the ``value``, per-shape per-update p50/p99 +
  bars/sec under ``levels``, and the streaming counters —
  updates/bars/snapshots, carry bytes, compiles-during-load, the
  streamed-vs-full-day parity verdict — under ``stream``; per-bar
  ingest is a new workload, so its records start their own
  baseline), ``r10_resident_v3`` / ``r10_resident_sharded_v2`` /
  ``r10_stream_v4`` (ISSUE 10: the device->host result leg ships
  blocked-quantized int16 payloads with per-slice bitwise-f32
  widening — data/result_wire.py — so the fetch bytes, the module,
  and the loop's host decode stage all change; bench stamps the r10
  names only when the record's ``result_wire.enabled`` is true, so
  a silent f32 fallback stays on the r6/r7 series),
  ``r11_fleet_v1`` (ISSUE 11: the replica fleet, ``bench.py
  fleet`` — N FactorServer replicas over disjoint device submeshes
  behind the coalescing-affinity router; the ``value`` is pod QPS at
  the record's highest client level × highest replica count, with
  per-replica-count p50/p99/QPS under ``replicas``, the pod-folded
  counters (routed/affinity/coalesced, exact per-replica sums —
  the PR 9 merge contract) under ``pod``, and ``live_replicas``
  stamping how many replicas actually served; a new workload and a
  new topology, so its records start their own baseline — a
  single-replica record can never smear onto the serve series),
  ``r12_resident_2d_v1`` (ISSUE 13: the 2-D ``(days, tickers)``
  pipelined resident scan — day-axis split of every batch, groups of
  scan steps pipelined across day-shards, the cross-day carry handed
  off through a ppermute leg — changes both the module and the loop;
  bench stamps it only when the mesh genuinely resolved to d > 1 AND
  t > 1 (``mesh_shape`` is the discriminator), so a 1-D fallback
  stays on the r7/r10 sharded series),
  ``r13_discover_v1`` (ISSUE 14: the factor-discovery engine,
  ``bench.py discover`` — the bounded evolutionary search with the
  fused on-device backtest fitness, population-sharded over the
  resident mesh; the ``value`` is candidates/sec at the record's
  highest population level, with per-level candidates/sec and
  per-generation p50/p99 under ``levels`` and the loop's measured
  contract — syncs-per-generation, compiles-during-loop — under
  ``discover``; a new workload, so its records start their own
  baseline),
  ``r14_stream_snapshot_v1`` (ISSUE 18: the snapshot-PER-BAR finalize
  profile, ``BENCH_STREAM_SNAPSHOT_PER_BAR=1 python bench.py stream``
  — one warm ``snapshot()`` timed after every ingested minute of a
  seeded day; the ``value`` is per-bar finalize p50 ms, the
  ``snapshot`` block carries p99 and the last-quartile-of-day vs
  first-quartile-of-day flatness ratios. The metric name embeds the
  RESOLVED ``finalize_impl`` (``..._exact_p50_ms`` vs
  ``..._fast_p50_ms``), so the O(day) batch-prefix finalize and the
  O(1)-per-bar sufficient-statistic fast path bank as SEPARATE
  series and the fast-vs-exact claim always has a banked
  before/after; a new instrument, so its records start their own
  baseline),
  ``r15_serve_edge_v1`` / ``r15_fleet_edge_v1`` (ISSUE 20: the
  evented binary front door — ``BENCH_SERVE_TRANSPORT=edge`` /
  ``BENCH_FLEET_TRANSPORT=edge`` drive keep-alive wire-encoded HTTP
  load through the selectors edge instead of the in-process queue
  loop; a new entry path AND a new answer encoding, so the records
  start their own baselines. The stdlib thread-per-connection A/B leg
  stamps ``...+transport=legacy`` and keys apart — the door
  comparison must never gate one leg against the other. Records whose
  ``edge.available`` is true (the load actually decoded wire answers)
  additionally derive ``<metric>.wire_bytes_per_answer``).

Session sub-series (ISSUE 15): every bench record stamps the market
``session`` it ran (``bench.py``'s BENCH_SESSION; records predating
the field are all 240-day cn_ashare runs and stay on their bare
series). A non-default session (``us_390``, ``crypto_1440``, ...)
suffixes the effective methodology with ``+session=<name>``, so its
records form their own per-(metric, methodology) groups — the
methodology break is DECLARED by the stamp itself, and a non-240
number can never smear into a banked 240 baseline in either
direction.

Byte sub-series (ISSUE 10): every bench record that carries the
``wire.bytes_per_day`` / ``result.bytes_per_day`` gauges contributes
``<metric>.wire_bytes_per_day`` and ``<metric>.result_bytes_per_day``
as their own gateable groups. Both deviation directions flag, like
every derived series: byte GROWTH is a transfer regression, and a
silent byte DROP usually means the payload lost content (e.g. an
unnoticed factor-set shrink) — neither may pass quietly.

Derived sub-series (ISSUE 8): each bench record additionally
contributes ``<metric>.request_p99_ms`` (its end-to-end request-latency
tail) and, when the record's ``hbm.available`` is true,
``<metric>.hbm_peak_bytes`` (the device-memory high watermark) as their
own gateable groups under the parent's methodology — see
:func:`derive_records`. A CPU fallback's live-arrays estimate
(``available: false``) never seeds or gates an HBM baseline.

Mesh sub-series (ISSUE 9, same availability contract): a sharded
record whose ``mesh.available`` is true (real shard watermarks were
sampled — occupancy/pad numbers alone never qualify) contributes
``<metric>.shard_skew_ratio`` (per-shard balance drifting apart is a
regression the wall-clock headline hides until it IS the wall) and
``<metric>.pad_waste_frac`` (the lcm ticker-padding waste — a universe
or shard-count change that silently doubles dead lanes flags here).
A 2-D record (ISSUE 13) whose ``mesh.axes`` carries per-axis
watermarks additionally contributes ``<metric>.skew_days`` /
``<metric>.skew_tickers`` — the day-pipeline and ticker-split balance
gate SEPARATELY, because a flat 8-shard skew of 1.0 can hide a day
axis whose two rows alternate straggling (each row's max hides inside
the global max/median).

Factor-health sub-series (ISSUE 12, same availability contract): a
record whose ``factor_health.available`` is true (the fused per-factor
stats side-output actually sampled) contributes
``<metric>.coverage_frac`` (the worst per-factor coverage — missing
DATA, which no machine-level gauge sees) and, when result-wire slices
were observed, ``<metric>.widen_rate`` (the fraction of per-(factor,
day) slices that failed their pinned round-trip bound and shipped
bitwise f32 — the ROADMAP's log-transform decision input). Declared-
break semantics ride the parent's methodology like every derived
series.

Discovery sub-series (ISSUE 14, same availability contract): a record
whose ``discover`` block shows a loop that genuinely ran warm and
inside its sync budget (``generations > 0``, ``compiles_during_loop
== 0``, ``syncs_per_generation <= 1`` — the tpu_session carry rule's
exact gate) contributes ``<metric>.candidates_per_s``. Both deviation
directions flag: a throughput DROP is the obvious regression, a JUMP
without a declared break usually means the fitness graph lost work
(e.g. a silently narrower skeleton or day slab). Cold or chatty loops
never seed the baseline.

SLO burn sub-series (ISSUE 16, same availability contract): a record
whose ``slo`` block is available with a NONZERO frame count (the
timeline sampler actually ran — a sampler that never fired measured
nothing and must not seed a burn baseline at 0) contributes
``<metric>.burn_rate_max`` — the worst multi-window burn rate any
objective reached over the run (telemetry/slo.py, docs/slo.md). Both
directions flag: a burn JUMP means the run spent error budget it
never spent before (sheds, tail latency, stale ingest) even when the
QPS headline held; a silent DROP to ~0 on a series that used to burn
usually means the objective's signal went dark, not that the service
got perfect.

Snapshot-flatness sub-series (ISSUE 18, same availability contract): a
record whose ``snapshot`` block is available (the per-bar profile ran
WARM — zero compiles while profiling — with enough bars to quartile)
contributes ``<metric>.snapshot_p99_flat_ratio`` — the per-bar finalize
p99 of the last quartile of the day over the first. Both directions
flag: a ratio JUMP on the fast series means per-snapshot work picked
up a bar-cursor dependence again (the exact regression the
sufficient-statistic path exists to kill), and a silent DROP toward 0
usually means the profile stopped measuring the finalize at all (e.g.
the snapshot lost its materializing read). Cold profiles never seed
the baseline — a compiling run measures XLA, not the finalize.

Baseline = median of every record in the group EXCEPT the latest; the
latest is the record under test. ``--check FILE`` instead gates a fresh
candidate record against the baseline of the FULL banked group (the
bench-harness mode: "is the record I just measured a regression?").

Exit codes: 0 = report emitted (deviations, if any, are *reported* —
the committed trajectory is history, not a failure of this checkout);
with ``--strict`` or ``--check``, 1 = a flagged regression; 2 = no
usable input.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

#: deviation (fraction of baseline) past which a record is flagged
DEFAULT_TOLERANCE = 0.05

#: methodology assigned to pre-r5 records that predate the field (every
#: one of them ran bench.py's stream loop; see module docstring)
LEGACY_METHODOLOGY = "r4_stream_v2"

#: stage keys are seconds unless suffixed otherwise
_NON_SECONDS = ("_ms", "_MB")


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


# --------------------------------------------------------------------------
# loading
# --------------------------------------------------------------------------


def _extract_record(doc) -> Optional[dict]:
    """The bench record inside one BENCH_r*.json document.

    Banked files are driver wrappers ``{"n": .., "parsed": {record}}``;
    bare record files (a harness checking its own fresh output) are
    accepted too. The nested ``stale_tpu_headline`` carry is NOT a
    record of the run that banked it — it never becomes a data point.
    """
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("parsed"), dict) and "metric" in doc["parsed"]:
        return doc["parsed"]
    if "metric" in doc and "value" in doc:
        return doc
    # last resort: the wrapper's tail holds the printed JSON line
    tail = doc.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and "metric" in rec:
                    return rec
    return None


def load_bench_series(root: str) -> List[dict]:
    """``[{n, source, record}, ...]`` from ``ROOT/BENCH_r*.json``
    (top-level only — fixtures and telemetry dirs below ROOT are not
    part of the banked trajectory), ordered by round number."""
    entries: List[dict] = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        rec = _extract_record(doc)
        if rec is None:
            continue
        m = re.search(r"BENCH_r(\d+)", os.path.basename(path))
        n = doc.get("n") if isinstance(doc, dict) else None
        if not isinstance(n, int):
            n = int(m.group(1)) if m else 0
        entries.append({"n": n, "source": os.path.basename(path),
                        "record": rec})
        # derived sub-series (ISSUE 8) join the trajectory as their own
        # (metric, methodology) groups — same banked file, own baseline
        for drec in derive_records(rec):
            entries.append({
                "n": n,
                "source": (os.path.basename(path) + "#"
                           + drec["derived_from"]),
                "record": drec})
    entries.sort(key=lambda e: (e["n"], e["source"]))
    return entries


def load_telemetry_spans(paths: List[str]) -> dict:
    """Fold ``span_seconds{span=...}`` histogram records out of
    telemetry ``metrics.jsonl`` streams into per-span stats — the
    cross-check between the bench series' ``stages`` dicts and what the
    instrumented run itself recorded."""
    spans: Dict[str, dict] = {}
    files = 0
    for path in paths:
        try:
            with open(path) as fh:
                lines = fh.readlines()
        except OSError:
            continue
        files += 1
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if (rec.get("kind") == "histogram"
                    and rec.get("name") == "span_seconds"):
                span = (rec.get("labels") or {}).get("span")
                if span:
                    spans[span] = {"count": rec.get("count"),
                                   "sum_s": rec.get("sum"),
                                   "p50_s": rec.get("p50"),
                                   "p95_s": rec.get("p95")}
    return {"files": files, "spans": spans}


def find_metrics_jsonl(path: str, max_depth: int = 3) -> List[str]:
    """metrics.jsonl files at or under ``path`` (bounded depth)."""
    if os.path.isfile(path):
        return [path]
    out: List[str] = []
    base_depth = path.rstrip(os.sep).count(os.sep)
    for r, dirs, fs in os.walk(path):
        if r.count(os.sep) - base_depth >= max_depth:
            dirs[:] = []
        if "metrics.jsonl" in fs:
            out.append(os.path.join(r, "metrics.jsonl"))
    return sorted(out)


# --------------------------------------------------------------------------
# baselines + evaluation
# --------------------------------------------------------------------------


#: the canonical market session (ISSUE 15). Records without a
#: ``session`` stamp — the whole banked trajectory predating the field
#: — are all 240-day cn_ashare runs, so they stay on their bare
#: methodology series; this is the same one pinned inference as
#: LEGACY_METHODOLOGY above.
DEFAULT_SESSION = "cn_ashare_240"


def effective_methodology(record: dict) -> str:
    m = record.get("methodology")
    meth = str(m) if m else LEGACY_METHODOLOGY
    # session sub-series keying (ISSUE 15): a non-default session is a
    # DIFFERENT workload shape — 390 or 1440 slots change the module,
    # the bytes and the loop — so its records suffix the methodology
    # and start their own baseline. A us_390 record can never pollute
    # (or be gated against) the banked 240 series, in either
    # direction; derived sub-series inherit the suffixed methodology
    # like every other declared break.
    session = record.get("session")
    if session and str(session) != DEFAULT_SESSION \
            and "+session=" not in meth:
        meth = f"{meth}+session={session}"
    return meth


def derive_records(record: dict) -> List[dict]:
    """Gateable sub-series lifted out of one bench record (ISSUE 8):

    * ``<metric>.request_p99_ms`` — the record's ``p99_ms`` (the
      serve/stream end-to-end request-latency distribution's tail; a
      QPS headline that holds while p99 doubles is a regression the
      top-line ``value`` cannot see);
    * ``<metric>.hbm_peak_bytes`` — the record's ``hbm.peak_bytes``
      watermark, ONLY when ``hbm.available`` is true (a live-arrays
      estimate from a CPU fallback must never gate against — or seed —
      a measured HBM baseline).

    Derived records inherit the parent's methodology, so they ride the
    existing per-(metric, methodology) machinery unchanged: the first
    record of a new series is a declared break (reported, not
    flagged), later ones gate at the same tolerance.
    """
    out: List[dict] = []
    metric = record.get("metric")
    if not isinstance(metric, str) or not metric:
        return out
    meth = effective_methodology(record)
    p99 = record.get("p99_ms")
    if isinstance(p99, (int, float)) and not isinstance(p99, bool):
        out.append({"metric": f"{metric}.request_p99_ms",
                    "value": float(p99), "unit": "ms",
                    "methodology": meth,
                    "derived_from": "p99_ms",
                    "stages": record.get("stages")})
    hbm = record.get("hbm")
    if isinstance(hbm, dict) and hbm.get("available"):
        peak = hbm.get("peak_bytes")
        if isinstance(peak, (int, float)) and not isinstance(peak, bool) \
                and peak > 0:
            out.append({"metric": f"{metric}.hbm_peak_bytes",
                        "value": float(peak), "unit": "bytes",
                        "methodology": meth,
                        "derived_from": "hbm.peak_bytes"})
    # byte-program sub-series (ISSUE 10): the per-day bytes each way.
    # Either sign of deviation flags via the shared tolerance machinery
    # (growth = transfer regression; silent shrink = lost payload)
    for block_key, metric_suffix in (("wire", "wire_bytes_per_day"),
                                     ("result", "result_bytes_per_day")):
        block = record.get(block_key)
        if isinstance(block, dict):
            bpd = block.get("bytes_per_day")
            if isinstance(bpd, (int, float)) \
                    and not isinstance(bpd, bool) and bpd > 0:
                out.append({"metric": f"{metric}.{metric_suffix}",
                            "value": float(bpd), "unit": "bytes/day",
                            "methodology": meth,
                            "derived_from":
                                f"{block_key}.bytes_per_day"})
    # factor-health sub-series (ISSUE 12): gated on
    # factor_health.available — only records whose dispatches actually
    # carried the fused stats side-output seed or gate these.
    # widen_rate additionally requires observed result-wire slices
    # (None when the wire was off — a wire-less record must not gate a
    # widen baseline at 0). Both directions flag: a widen-rate JUMP
    # means slices stopped fitting their pinned bounds (the
    # log-transform question), a silent DROP to ~0 usually means the
    # per-factor attribution went dark; a coverage DROP is missing
    # data, a jump means the mask/universe changed shape.
    fh = record.get("factor_health")
    if isinstance(fh, dict) and fh.get("available"):
        wr = fh.get("widen_rate")
        if isinstance(wr, (int, float)) and not isinstance(wr, bool) \
                and (fh.get("widen") or {}).get("slices"):
            out.append({"metric": f"{metric}.widen_rate",
                        "value": float(wr), "unit": "frac",
                        "methodology": meth,
                        "derived_from": "factor_health.widen_rate"})
        cov = fh.get("coverage_frac")
        if isinstance(cov, (int, float)) and not isinstance(cov, bool) \
                and cov > 0:
            out.append({"metric": f"{metric}.coverage_frac",
                        "value": float(cov), "unit": "frac",
                        "methodology": meth,
                        "derived_from":
                            "factor_health.coverage_frac"})
    # discovery sub-series (ISSUE 14): gated on the discover block's
    # own evidence — only loops that completed generations WARM
    # (zero loop compiles) and inside the 1-sync/generation budget
    # seed or gate the candidates/sec baseline (a cold loop measures
    # XLA, a chatty one measures the host round trip)
    disc = record.get("discover")
    if isinstance(disc, dict) \
            and isinstance(disc.get("generations"), int) \
            and disc["generations"] > 0 \
            and disc.get("compiles_during_loop") == 0 \
            and isinstance(disc.get("syncs_per_generation"),
                           (int, float)) \
            and not isinstance(disc.get("syncs_per_generation"), bool) \
            and disc["syncs_per_generation"] <= 1:
        cps = disc.get("candidates_per_s")
        if isinstance(cps, (int, float)) and not isinstance(cps, bool) \
                and cps > 0:
            out.append({"metric": f"{metric}.candidates_per_s",
                        "value": float(cps), "unit": "candidates/s",
                        "methodology": meth,
                        "derived_from": "discover.candidates_per_s"})
    # mesh balance sub-series (ISSUE 9): gated on mesh.available — only
    # records with REAL shard watermarks (telemetry/meshplane.py) seed
    # or gate the balance baselines
    mesh = record.get("mesh")
    if isinstance(mesh, dict) and mesh.get("available"):
        skew = mesh.get("shard_skew_ratio")
        if isinstance(skew, (int, float)) and not isinstance(skew, bool) \
                and skew > 0:
            out.append({"metric": f"{metric}.shard_skew_ratio",
                        "value": float(skew), "unit": "ratio",
                        "methodology": meth,
                        "derived_from": "mesh.shard_skew_ratio"})
        waste = mesh.get("pad_waste_frac")
        if isinstance(waste, (int, float)) \
                and not isinstance(waste, bool) and waste >= 0:
            out.append({"metric": f"{metric}.pad_waste_frac",
                        "value": float(waste), "unit": "frac",
                        "methodology": meth,
                        "derived_from": "mesh.pad_waste_frac"})
        # per-axis skew sub-series from 2-D records (ISSUE 13): only
        # axes with REAL per-axis watermarks qualify — 1-D records
        # carry no ``axes`` block and derive nothing here
        axes = mesh.get("axes")
        if isinstance(axes, dict):
            for axis, info in sorted(axes.items()):
                if not isinstance(info, dict):
                    continue
                askew = info.get("skew_ratio")
                if isinstance(askew, (int, float)) \
                        and not isinstance(askew, bool) and askew > 0 \
                        and info.get("shard_time_s"):
                    out.append({"metric": f"{metric}.skew_{axis}",
                                "value": float(askew), "unit": "ratio",
                                "methodology": meth,
                                "derived_from":
                                    f"mesh.axes.{axis}.skew_ratio"})
    # SLO burn sub-series (ISSUE 16): gated on slo.available with a
    # nonzero timeline (a record whose sampler never ran measured
    # nothing — it must not seed or gate a burn baseline at 0). Both
    # directions flag through the shared tolerance machinery: a burn
    # JUMP means the run spent error budget it never spent before
    # (sheds, tail latency, stale ingest), a silent DROP to ~0 on a
    # series that used to burn usually means the objective's signal
    # went dark, not that the service got perfect.
    slo = record.get("slo")
    if isinstance(slo, dict) and slo.get("available") \
            and isinstance(slo.get("frames"), int) and slo["frames"] > 0:
        wbr = slo.get("worst_burn_rate")
        if isinstance(wbr, (int, float)) and not isinstance(wbr, bool) \
                and wbr >= 0:
            out.append({"metric": f"{metric}.burn_rate_max",
                        "value": float(wbr), "unit": "ratio",
                        "methodology": meth,
                        "derived_from": "slo.worst_burn_rate"})
    # binary-edge sub-series (ISSUE 20): gated on edge.available with
    # answers actually decoded — only an HTTP wire load that counted
    # its bytes at the CLIENT seeds or gates the per-answer baseline.
    # Both directions flag: byte GROWTH per answer is a wire
    # regression (framing bloat, a lost quantization tier), a silent
    # DROP usually means the answers lost content (a shrunken factor
    # set shipping under the same metric name) — neither may pass
    # quietly.
    edge = record.get("edge")
    if isinstance(edge, dict) and edge.get("available") \
            and isinstance(edge.get("wire_answers"), int) \
            and edge["wire_answers"] > 0:
        wbpa = edge.get("wire_bytes_per_answer")
        if isinstance(wbpa, (int, float)) and not isinstance(wbpa, bool) \
                and wbpa > 0:
            out.append({"metric": f"{metric}.wire_bytes_per_answer",
                        "value": float(wbpa), "unit": "bytes/answer",
                        "methodology": meth,
                        "derived_from":
                            "edge.wire_bytes_per_answer"})
    # snapshot-flatness sub-series (ISSUE 18): gated on the per-bar
    # profile's own evidence — only a WARM profile (zero compiles
    # while profiling, enough bars to quartile) measures finalize
    # flatness; a cold one measures XLA and must not seed the
    # baseline. Both directions flag: a ratio JUMP on the fast series
    # means per-snapshot work regrew a bar-cursor dependence, a
    # silent DROP toward 0 means the profile stopped measuring the
    # finalize at all.
    snap = record.get("snapshot")
    if isinstance(snap, dict) and snap.get("available"):
        flat = snap.get("p99_flat_ratio")
        if isinstance(flat, (int, float)) and not isinstance(flat, bool) \
                and flat > 0:
            out.append({"metric": f"{metric}.snapshot_p99_flat_ratio",
                        "value": float(flat), "unit": "ratio",
                        "methodology": meth,
                        "derived_from": "snapshot.p99_flat_ratio"})
    return out


def group_entries(entries: List[dict]) -> Dict[Tuple[str, str], List[dict]]:
    groups: Dict[Tuple[str, str], List[dict]] = {}
    for e in entries:
        rec = e["record"]
        key = (str(rec.get("metric")), effective_methodology(rec))
        groups.setdefault(key, []).append(e)
    return groups


def _stages_seconds(record: dict) -> Dict[str, float]:
    out = {}
    for k, v in (record.get("stages") or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool) \
                and not any(k.endswith(s) for s in _NON_SECONDS):
            out[k] = float(v)
    return out


def stage_diff(baseline_entries: List[dict], latest: dict) -> List[dict]:
    """Where the time moved: latest record's per-stage seconds vs the
    per-stage median over the baseline entries, sorted by |delta|
    descending. Stages present on only one side report a null for the
    missing side (a stage appearing/disappearing IS a finding)."""
    base: Dict[str, List[float]] = {}
    for e in baseline_entries:
        for k, v in _stages_seconds(e["record"]).items():
            base.setdefault(k, []).append(v)
    base_med = {k: _median(v) for k, v in base.items()}
    latest_st = _stages_seconds(latest)
    rows = []
    for k in sorted(set(base_med) | set(latest_st)):
        b = base_med.get(k)
        l_ = latest_st.get(k)
        row = {"stage": k,
               "baseline_s": round(b, 3) if b is not None else None,
               "latest_s": round(l_, 3) if l_ is not None else None}
        if b is not None and l_ is not None:
            row["delta_s"] = round(l_ - b, 3)
            row["delta_pct"] = (round(100.0 * (l_ - b) / b, 1)
                                if b else None)
        rows.append(row)
    rows.sort(key=lambda r: abs(r.get("delta_s") or 0.0), reverse=True)
    return rows


def _evaluate_group(key: Tuple[str, str], entries: List[dict],
                    candidate: Optional[dict],
                    tolerance: float) -> Optional[dict]:
    """Verdict row for one (metric, methodology) series. With a
    ``candidate`` record, the whole banked group is the baseline;
    otherwise the group's latest entry is under test. None when there
    is nothing to compare against (a declared break's first record)."""
    if candidate is not None:
        baseline_entries = entries
        latest_rec = candidate
        latest_src = "candidate"
    else:
        if len(entries) < 2:
            return None
        baseline_entries = entries[:-1]
        latest_rec = entries[-1]["record"]
        latest_src = entries[-1]["source"]
    vals = [e["record"].get("value") for e in baseline_entries]
    vals = [float(v) for v in vals
            if isinstance(v, (int, float)) and not isinstance(v, bool)]
    latest_val = latest_rec.get("value")
    if not vals or not isinstance(latest_val, (int, float)):
        return None
    baseline = _median(vals)
    deviation = ((float(latest_val) - baseline) / baseline
                 if baseline else 0.0)
    flagged = abs(deviation) > tolerance
    row = {
        "metric": key[0],
        "methodology": key[1],
        "n_baseline": len(vals),
        "baseline_value": round(baseline, 3),
        "baseline_band": [round(min(vals), 3), round(max(vals), 3)],
        "latest_value": round(float(latest_val), 3),
        "latest_source": latest_src,
        "deviation_pct": round(100.0 * deviation, 2),
        "flagged": flagged,
    }
    if flagged:
        row["stage_diff"] = stage_diff(baseline_entries, latest_rec)
    return row


def evaluate(entries: List[dict], tolerance: float = DEFAULT_TOLERANCE,
             candidate: Optional[dict] = None) -> dict:
    """The machine-readable verdict over a loaded trajectory (and an
    optional fresh candidate record)."""
    groups = group_entries(entries)
    rows: List[dict] = []
    if candidate is not None:
        # the candidate gates as itself AND as each derived sub-series
        # (ISSUE 8): a steady headline with a doubled request p99 or
        # HBM watermark flags on the derived group
        for cand in [candidate] + derive_records(candidate):
            key = (str(cand.get("metric")),
                   effective_methodology(cand))
            row = _evaluate_group(key, groups.get(key, []), cand,
                                  tolerance)
            if row is None:
                # no banked series for this (metric, methodology): a
                # declared break — reported, never flagged
                rows.append({"metric": key[0], "methodology": key[1],
                             "n_baseline": 0, "flagged": False,
                             "note": "no baseline series (declared "
                                     "break or first record)"})
            else:
                rows.append(row)
    else:
        for key in sorted(groups):
            row = _evaluate_group(key, groups[key], None, tolerance)
            if row is not None:
                rows.append(row)
    flagged = [r for r in rows if r.get("flagged")]
    return {
        "schema": 1,
        "tolerance_pct": round(100.0 * tolerance, 2),
        "records": sum(len(v) for v in groups.values()),
        "series": len(groups),
        "groups": rows,
        "flagged": [{"metric": r["metric"],
                     "methodology": r["methodology"],
                     "deviation_pct": r["deviation_pct"]}
                    for r in flagged],
        "ok": not flagged,
    }


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m replication_of_minute_frequency_factor_tpu"
             ".telemetry.regress",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("root", help="directory holding the BENCH_r*.json "
                                 "trajectory (the repo root)")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="flag |deviation| past this fraction of the "
                         "baseline (default 0.05)")
    ap.add_argument("--check", metavar="FILE", default=None,
                    help="gate a fresh candidate record (bare record "
                         "JSON or driver wrapper) against the banked "
                         "baselines; exits 1 when flagged")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when the trajectory's own latest "
                         "record in any series is flagged")
    ap.add_argument("--telemetry", metavar="PATH", default=None,
                    help="also fold span_seconds stats out of "
                         "metrics.jsonl streams at/under PATH into the "
                         "verdict (cross-check, never flags)")
    ap.add_argument("--out", metavar="FILE", default=None,
                    help="additionally write the verdict (indented) "
                         "to FILE")
    args = ap.parse_args(argv)

    entries = load_bench_series(args.root)
    candidate = None
    if args.check:
        try:
            with open(args.check) as fh:
                candidate = _extract_record(json.load(fh))
        except (OSError, ValueError) as e:
            print(json.dumps({"ok": False,
                              "error": f"unreadable --check file: {e}"}))
            return 2
        if candidate is None:
            print(json.dumps({"ok": False,
                              "error": "--check file holds no bench "
                                       "record"}))
            return 2
    if not entries and candidate is None:
        print(json.dumps({"ok": False,
                          "error": f"no BENCH_r*.json under "
                                   f"{args.root!r}"}))
        return 2

    verdict = evaluate(entries, tolerance=args.tolerance,
                       candidate=candidate)
    if args.telemetry:
        verdict["telemetry"] = load_telemetry_spans(
            find_metrics_jsonl(args.telemetry))
    # ONE line on stdout: harnesses parse it as a JSON line
    print(json.dumps(verdict))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(verdict, fh, indent=1)
    if (args.strict or candidate is not None) and not verdict["ok"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
