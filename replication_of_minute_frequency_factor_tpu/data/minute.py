"""Minute-bar gridding: long rows -> dense ``[tickers, 240, fields]`` tensor.

The reference consumes one parquet per trading day with long-format rows
``(code, date, time, open, high, low, close, volume)``
(SURVEY.md §2.3; MinuteFrequentFactorCICC.py:68-77). The TPU-native layout is
a dense f32 day tensor over the 240-slot trade-minute grid plus a validity
mask — missing bars (halts, late opens) become cleared mask lanes instead of
absent rows, which is what lets all 58 kernels run as one fused XLA graph
with static shapes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from ..markets import get_session

FIELDS = ("open", "high", "low", "close", "volume")
F_OPEN, F_HIGH, F_LOW, F_CLOSE, F_VOLUME = range(5)


@dataclasses.dataclass
class DayGrid:
    """One trading day, densely gridded.

    bars:  f32[T, 240, 5]  (open, high, low, close, volume); 0 where invalid
    mask:  bool[T, 240]    bar present at (ticker, slot)
    codes: [T] ticker identifiers, sorted ascending
    date:  the trading date (numpy datetime64[D] scalar or None)
    """

    bars: np.ndarray
    mask: np.ndarray
    codes: np.ndarray
    date: Optional[np.datetime64] = None

    @property
    def n_tickers(self) -> int:
        return self.bars.shape[0]


def grid_day(
    code: np.ndarray,
    time: np.ndarray,
    open_: np.ndarray,
    high: np.ndarray,
    low: np.ndarray,
    close: np.ndarray,
    volume: np.ndarray,
    date: Optional[np.datetime64] = None,
    codes: Optional[Sequence] = None,
    dtype=np.float32,
    use_native: Optional[bool] = None,
    session=None,
) -> DayGrid:
    """Scatter long-format rows of one day onto the dense minute grid.

    * off-grid timestamps (anything but whole minutes in 09:30-11:29 /
      13:00-14:59) are dropped — the reference's formula would alias 11:30
      onto 13:00 (sessions.py);
    * duplicate (code, slot) rows keep the last occurrence;
    * ``codes`` pins the ticker axis (for cross-day batching); defaults to
      the sorted unique codes present;
    * ``use_native`` selects the C++ one-pass packer (:mod:`..native`);
      default: native when built, numpy otherwise (identical results —
      tests/test_native.py). The native packer is baked to the
      canonical 240 layout, so non-default sessions always grid
      through the numpy path;
    * ``session`` picks the market grid (ISSUE 15; None = the
      240-slot cn_ashare day).
    """
    sess = get_session(session)
    code = np.asarray(code)

    if codes is None:
        codes = np.unique(code)
    else:
        # the ticker axis is always sorted ascending (np.searchsorted below
        # requires it; callers must read the axis order back off .codes)
        codes = np.sort(np.asarray(codes))
    tidx = np.searchsorted(codes, code)
    known = (tidx < len(codes)) & (np.take(codes, np.minimum(tidx, len(codes) - 1)) == code)

    T = len(codes)
    is_default_240 = sess.n_slots == 240 and sess.segments[0][0] == 570
    if (use_native is None or use_native) and is_default_240:
        from .. import native
        if native.available() and dtype == np.float32:
            bars, mask = native.grid_pack_native(
                np.where(known, tidx, -1), time,
                open_, high, low, close, volume, T)
            return DayGrid(bars=bars, mask=mask, codes=codes, date=date)
        if use_native:
            raise RuntimeError("native gridpack requested but unavailable")

    slots = sess.time_to_slot(np.asarray(time))
    ok = (slots >= 0) & known
    bars = np.zeros((T, sess.n_slots, len(FIELDS)), dtype=dtype)
    mask = np.zeros((T, sess.n_slots), dtype=bool)
    ti, si = tidx[ok], slots[ok]
    for f, col in zip(range(5), (open_, high, low, close, volume)):
        bars[ti, si, f] = np.asarray(col)[ok]
    mask[ti, si] = True
    return DayGrid(bars=bars, mask=mask, codes=codes, date=date)
