"""Synthetic A-share minute-bar generator for tests and benchmarks.

Produces long-format day data with the pathologies the parity suite must
cover (SURVEY.md §4): missing bars / halts, zero-volume bars, constant
prices, short (<50 bar) days, and duplicate close values (exercising the
chip-factor tie handling).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ..markets import get_session


def synth_day(
    rng: np.random.Generator,
    n_codes: int = 8,
    missing_prob: float = 0.0,
    zero_volume_prob: float = 0.0,
    constant_price_codes: int = 0,
    short_day_codes: int = 0,
    tick_decimals: int = 2,
    date: str = "2024-01-02",
    session=None,
) -> Dict[str, np.ndarray]:
    """Return long-format columns sorted by (code, time).

    * ``constant_price_codes`` leading codes trade flat all day (var=0 paths);
    * ``short_day_codes`` trailing codes only trade the last 30 slots
      (<50 bars: the rolling-window drop rule);
    * prices are rounded to ``tick_decimals`` so duplicate values occur.
    """
    sess = get_session(session)
    rows_code, rows_time = [], []
    rows = {k: [] for k in ("open", "high", "low", "close", "volume")}
    for i in range(n_codes):
        code = f"{600000 + i:06d}"
        slots = np.arange(sess.n_slots)
        if i >= n_codes - short_day_codes:
            slots = slots[-30:]
        if missing_prob > 0:
            keep = rng.random(len(slots)) >= missing_prob
            slots = slots[keep]
        if len(slots) == 0:
            continue
        n = len(slots)
        base = rng.uniform(5.0, 50.0)
        if i < constant_price_codes:
            close = np.full(n, round(base, tick_decimals))
            open_ = close.copy()
            high = close.copy()
            low = close.copy()
        else:
            steps = rng.normal(0, 0.001, n)
            mid = base * np.exp(np.cumsum(steps))
            open_ = np.round(mid * (1 + rng.normal(0, 3e-4, n)), tick_decimals)
            close = np.round(mid * (1 + rng.normal(0, 3e-4, n)), tick_decimals)
            hi = np.maximum(open_, close) * (1 + np.abs(rng.normal(0, 3e-4, n)))
            lo = np.minimum(open_, close) * (1 - np.abs(rng.normal(0, 3e-4, n)))
            high = np.round(hi, tick_decimals)
            low = np.round(lo, tick_decimals)
            open_ = np.maximum(open_, 0.01)
            close = np.maximum(close, 0.01)
            low = np.maximum(low, 0.01)
            high = np.maximum(high, low)
        volume = rng.integers(0 if zero_volume_prob > 0 else 100, 100_000,
                              n).astype(np.float64)
        if zero_volume_prob > 0:
            volume[rng.random(n) < zero_volume_prob] = 0.0
        rows_code.append(np.full(n, code))
        rows_time.append(sess.grid_times[slots])
        rows["open"].append(open_)
        rows["high"].append(high)
        rows["low"].append(low)
        rows["close"].append(close)
        rows["volume"].append(volume)

    out = {
        "code": np.concatenate(rows_code),
        "time": np.concatenate(rows_time).astype(np.int64),
        "date": np.full(sum(map(len, rows_code)), np.datetime64(date, "D")),
    }
    for k, v in rows.items():
        out[k] = np.concatenate(v).astype(np.float64)
    return out
