"""Compact host->device wire format for day batches.

The tunnel/PCIe link, not the MXU, bounds pipeline throughput (the fused
58-factor graph runs in ~2 ms per 8-day x 5000-ticker batch; the raw f32
tensor for it is ~200 MB). A-share prices are tick-aligned (0.01 CNY), so
the batch ships as:

  base    [D, T]         f32   first valid close (ticks*0.01)
  deltas  [D, T, 240, 4] int16 close tick-delta vs previous valid close;
                               open/high/low tick-delta vs same-bar close
  volume  [D, T, 240]    int32 shares
  mask    [D, T, 240]    bool

12 bytes/bar instead of 20 — a 1.67x cut in wire bytes — reconstructed by
a fused on-device decode: one int32 cumsum over the 240-slot axis + a
scale. Decoded prices match the direct f32 cast to within 1 ulp (~1e-7
relative): XLA strength-reduces the constant tick division to a
reciprocal multiply, which is not correctly rounded. The wobble is
semantically safe — equal tick counts decode to identical floats, so every
sign/threshold comparison in the kernels (ret>0, time masks, top-k cuts on
integer volume) is unaffected. ``encode`` returns None whenever the data
doesn't fit the format (off-tick prices, >int16 deltas, non-integer or
>int31 volume) and callers fall back to shipping raw f32, so the format is
an opt-in transfer optimisation.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

TICK = 0.01
_I16 = 32767


@dataclasses.dataclass
class WireBatch:
    base: np.ndarray     # [..., T] f32
    deltas: np.ndarray   # [..., T, 240, 4] int16
    volume: np.ndarray   # [..., T, 240] int32
    mask: np.ndarray     # [..., T, 240] bool

    @property
    def nbytes(self) -> int:
        return (self.base.nbytes + self.deltas.nbytes + self.volume.nbytes
                + self.mask.nbytes)


def encode(bars: np.ndarray, mask: np.ndarray, tick: float = TICK,
           use_native: Optional[bool] = None) -> Optional[WireBatch]:
    """Host-side packing; None when the batch can't be represented.

    Dispatches to the C++ single-pass encoder (:mod:`..native`) when built
    (~100x the numpy path below, which remains the portable fallback and
    parity oracle)."""
    bars = np.asarray(bars)
    mask = np.asarray(mask)
    if use_native is None or use_native:
        from .. import native
        if native.available():
            out = native.wire_encode_native(bars, mask, round(1.0 / tick))
            if out is not None:
                base, deltas, volume = out
                return WireBatch(base=base, deltas=deltas, volume=volume,
                                 mask=mask.astype(bool))
            return None  # native says unrepresentable; semantics match numpy
        if use_native:
            raise RuntimeError("native wire encoder unavailable")
    o, h, l, c, v = (bars[..., i] for i in range(5))

    ct = np.rint(c / tick)
    # tick alignment of every price field on valid lanes
    for p in (o, h, l, c):
        pt = p / tick
        if not np.allclose(pt[mask], np.rint(pt[mask]), atol=1e-3):
            return None
    if np.abs(ct[mask]).max(initial=0) > 2**22:  # f32-exact tick range
        return None
    vv = v[mask]
    if len(vv) and (not np.allclose(vv, np.rint(vv), atol=1e-3)
                    or vv.max(initial=0) >= 2**31 or vv.min(initial=0) < 0):
        return None

    ctm = np.where(mask, ct, 0.0)
    # previous valid close ticks per slot (base before the first valid bar)
    idx = np.where(mask, np.arange(mask.shape[-1]), -1)
    last_valid = np.maximum.accumulate(idx, axis=-1)
    prev_valid = np.concatenate(
        [np.full(last_valid.shape[:-1] + (1,), -1), last_valid[..., :-1]],
        axis=-1)
    first_idx = np.argmax(mask, axis=-1)
    base_ct = np.take_along_axis(ctm, first_idx[..., None], axis=-1)[..., 0]
    prev_ct = np.where(
        prev_valid >= 0,
        np.take_along_axis(ctm, np.maximum(prev_valid, 0), axis=-1),
        base_ct[..., None])
    dclose = np.where(mask, ct - prev_ct, 0.0)
    dopen = np.where(mask, np.rint(o / tick) - ct, 0.0)
    dhigh = np.where(mask, np.rint(h / tick) - ct, 0.0)
    dlow = np.where(mask, np.rint(l / tick) - ct, 0.0)
    deltas = np.stack([dclose, dopen, dhigh, dlow], axis=-1)
    if np.abs(deltas).max(initial=0) > _I16:
        return None
    return WireBatch(
        base=(base_ct / round(1.0 / tick)).astype(np.float32),
        deltas=deltas.astype(np.int16),
        volume=np.where(mask, v, 0).astype(np.int32),
        mask=mask.astype(bool),
    )


@functools.partial(jax.jit, static_argnames=("tick",))
def decode(base, deltas, volume, mask, tick: float = TICK):
    """On-device unpacking -> ``(bars [..., T, 240, 5] f32, mask)``.

    Fuses into the factor graph: XLA keeps the int16->f32 expansion in
    HBM-local registers instead of shipping wide floats over the wire.
    """
    d = deltas.astype(jnp.int32)
    inv = jnp.float32(round(1.0 / tick))
    ct = jnp.round(base * inv).astype(jnp.int32)[..., None] \
        + jnp.cumsum(d[..., 0], axis=-1)
    close = ct.astype(jnp.float32) / inv
    open_ = (ct + d[..., 1]).astype(jnp.float32) / inv
    high = (ct + d[..., 2]).astype(jnp.float32) / inv
    low = (ct + d[..., 3]).astype(jnp.float32) / inv
    vol = volume.astype(jnp.float32)
    zero = jnp.zeros_like(close)
    m = mask
    bars = jnp.stack(
        [jnp.where(m, f, zero) for f in (open_, high, low, close, vol)],
        axis=-1)
    return bars, m


def put(wire: WireBatch, shardings=None):
    """device_put the packed representation (decode happens device-side)."""
    arrs = (wire.base, wire.deltas, wire.volume, wire.mask)
    if shardings is None:
        return tuple(jax.device_put(a) for a in arrs)
    return tuple(jax.device_put(a, s) for a, s in zip(arrs, shardings))
