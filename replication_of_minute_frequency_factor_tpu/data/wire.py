"""Compact host->device wire format for day batches.

The tunnel/PCIe link, not the MXU, bounds pipeline throughput (the fused
58-factor graph runs in ~2 ms per 8-day x 5000-ticker batch; the raw f32
tensor for it is ~200 MB). A-share prices are tick-aligned (0.01 CNY) and
volumes trade in board lots, so the batch ships as:

  base     [D, T]         f32    first valid close (ticks*0.01)
  dclose   [D, T, 120]    uint8  close tick-delta vs previous valid close,
                                 two int4 deltas per byte (|d| <= 7);
                                 widens to [..., 240] int8, then int16
  dohl     [D, T, 240, 1] uint8  tight packing: int4 open-close delta |
                                 high-wick 2 bits << 4 | low-wick 2 bits
                                 << 6, wicks measured from the bar body;
                                 widens to the [..., 2] wick packing
                                 (int8 delta + nibble wicks), then
                                 [..., 3] int8, then int16 per-field
  volume   [D, T, 300]    uint8  four 10-bit volumes per 5 bytes
                                 (little-endian bit stream), in shares
                                 or 100-share lots (vol_scale); widens
                                 to [..., 240] uint16 shares/lots, then
                                 int32 shares
  maskbits [D, T, 30]     uint8  validity mask, bit-packed little-endian

Down to ~2.9 bytes/bar from 21 (f32 bars + bool mask) on typical data —
a 7.2x cut in wire bytes — reconstructed by a fused on-device decode: one
int32 cumsum over the 240-slot axis, bit/nibble unpacks, and two scales.
Every narrowing is per-batch with a widening fallback, so one expensive
ticker or heavy-volume day widens its field instead of rejecting the
batch.
Decoded prices match the direct f32 cast to within 1 ulp (~1e-7
relative): XLA strength-reduces the constant tick division to a
reciprocal multiply, which is not correctly rounded. The wobble is
semantically safe — equal tick counts decode to identical floats, so
every sign/threshold comparison in the kernels (ret>0, time masks, top-k
cuts on integer volume) is unaffected. ``encode`` returns None whenever
the data doesn't fit the format at all (off-tick prices, >int16 deltas,
non-integer or >int31 volume) and callers fall back to shipping raw f32,
so the format is an opt-in transfer optimisation.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..native import narrow_wire
from ..telemetry import get_telemetry

TICK = 0.01
_I16 = 32767
#: the canonical cn_ashare_240 slot count. The format itself is
#: session-generic (ISSUE 15): encode reads the slot extent off the
#: mask, decode re-derives it from ``dohl``'s slot axis (every dohl
#: mode keeps a full slot axis), and the sub-byte packings gate on
#: divisibility (``pack_dclose4`` needs an even slot count, ``vol10``
#: a multiple of 4) — a session that misses a packing's divisor simply
#: never produces that mode, it does not reject the batch.
N_SLOTS = 240
MASK_BYTES = N_SLOTS // 8
VOL10_MAX = 1023
VOL10_BYTES = N_SLOTS // 4 * 5  # four 10-bit values per 5 bytes = 300


def mask_bytes(n_slots: int) -> int:
    """Bit-packed mask bytes per (ticker, day) for a slot count
    (np.packbits zero-pads the final byte)."""
    return -(-n_slots // 8)


def vol10_bytes(n_slots: int) -> int:
    """10-bit-packed volume bytes for a slot count (only produced when
    ``n_slots % 4 == 0``; see :func:`..native.narrow_wire`)."""
    return n_slots // 4 * 5


@dataclasses.dataclass
class WireBatch:
    base: np.ndarray      # [..., T] f32
    dclose: np.ndarray    # [..., T, 120] u8 int4-pair, or [..., 240] i8/i16
    dohl: np.ndarray      # [..., T, 240, 1] u8 tight / [..., 2] u8 wick /
                          # [..., 3] i8/i16 per-field
    volume: np.ndarray    # [..., T, 300] u8 10-bit packed, or
                          # [..., T, 240] uint16/int32
    maskbits: np.ndarray  # [..., T, 30] uint8 (little-endian bit order)
    vol_scale: float      # shares per volume unit (1 or 100)

    @property
    def arrays(self):
        return (self.base, self.dclose, self.dohl, self.volume,
                self.maskbits,
                np.float32(self.vol_scale))

    @property
    def nbytes(self) -> int:
        return sum(np.asarray(a).nbytes for a in self.arrays)


def pack_mask(mask: np.ndarray) -> np.ndarray:
    """[..., S] bool -> [..., ceil(S/8)] uint8, little-endian bit
    order (packbits zero-pads the final byte; decode slices back)."""
    return np.packbits(np.asarray(mask, bool), axis=-1, bitorder="little")


def encode(bars: np.ndarray, mask: np.ndarray, tick: float = TICK,
           use_native: Optional[bool] = None,
           floor: Optional[dict] = None) -> Optional[WireBatch]:
    """Host-side packing; None when the batch can't be represented.

    Dispatches to the C++ single-pass encoder (:mod:`..native`) when built
    (~100x the numpy path below, which remains the portable fallback and
    parity oracle). ``floor`` is the widen-only dtype state a pipeline run
    threads through successive batches (see ``native.narrow_wire``).

    Telemetry: every call lands in ``wire.encode_batches{kind=wire|raw}``
    (``raw`` = returned None, caller ships f32) and successful encodes in
    ``wire.encode_bytes`` — the counters behind the pipeline's and
    bench's encode-kind reporting (docs/observability.md)."""
    out = _encode_impl(bars, mask, tick, use_native, floor)
    tel = get_telemetry()
    if out is None:
        tel.counter("wire.encode_batches", kind="raw")
    else:
        tel.counter("wire.encode_batches", kind="wire")
        tel.counter("wire.encode_bytes", out.nbytes)
    return out


def _encode_impl(bars, mask, tick, use_native, floor):
    bars = np.asarray(bars)
    mask = np.asarray(mask)
    if use_native is None or use_native:
        from .. import native
        if native.available() and mask.shape[-1] == N_SLOTS:
            out = native.wire_encode_native(bars, mask, round(1.0 / tick),
                                            floor=floor)
            if out is not None:
                base, dclose, dohl, volume, vol_scale = out
                return WireBatch(base=base, dclose=dclose, dohl=dohl,
                                 volume=volume, maskbits=pack_mask(mask),
                                 vol_scale=vol_scale)
            return None  # native says unrepresentable; semantics match numpy
        if use_native:
            raise RuntimeError("native wire encoder unavailable")
    # float64 throughout, matching the native double sweep bit-for-bit:
    # under NEP 50 a bare ``f32_array / tick`` would stay FLOAT32 and
    # round high tick counts to different integers than the f64 native
    # path (~0.34-tick quotient error at 4e6 ticks). Multiply by the
    # integral inverse (what the native code does) rather than dividing
    # by the non-representable 0.01.
    inv = round(1.0 / tick)
    o, h, l, c, v = (bars[..., i].astype(np.float64) for i in range(5))

    ct = np.rint(c * inv)
    # Tick alignment of every price field on valid lanes: absolute 1e-3
    # ticks plus a relative 4-f32-ulp term — prices arrive as f32, whose
    # representation error measured in ticks grows with magnitude and
    # passes 1e-3 near 84 CNY (native/gridpack.cpp applies the same
    # formula; an earlier np.allclose here hid an implicit rtol=1e-5
    # that disagreed with the native path at high prices).
    for p in (o, h, l, c):
        pt = (p * inv)[mask]
        r = np.rint(pt)
        if not (np.abs(pt - r) <= 1e-3 + 2.4e-7 * np.abs(r)).all():
            return None
    if np.abs(ct[mask]).max(initial=0) > 2**22:  # f32-exact tick range
        return None
    vv = v[mask]
    # volume integrality is ABSOLUTE 1e-3 (no relative term): f32 holds
    # fractional volumes up to 2^23, e.g. 4194304.5, which allclose's
    # implicit rtol=1e-5 would wave through while the native path rejects
    if len(vv) and (not (np.abs(vv - np.rint(vv)) <= 1e-3).all()
                    or vv.max(initial=0) >= 2**31 or vv.min(initial=0) < 0):
        return None

    ctm = np.where(mask, ct, 0.0)
    # previous valid close ticks per slot (base before the first valid bar)
    idx = np.where(mask, np.arange(mask.shape[-1]), -1)
    last_valid = np.maximum.accumulate(idx, axis=-1)
    prev_valid = np.concatenate(
        [np.full(last_valid.shape[:-1] + (1,), -1), last_valid[..., :-1]],
        axis=-1)
    first_idx = np.argmax(mask, axis=-1)
    base_ct = np.take_along_axis(ctm, first_idx[..., None], axis=-1)[..., 0]
    prev_ct = np.where(
        prev_valid >= 0,
        np.take_along_axis(ctm, np.maximum(prev_valid, 0), axis=-1),
        base_ct[..., None])
    dclose = np.where(mask, ct - prev_ct, 0.0)
    dopen = np.where(mask, np.rint(o * inv) - ct, 0.0)
    dhigh = np.where(mask, np.rint(h * inv) - ct, 0.0)
    dlow = np.where(mask, np.rint(l * inv) - ct, 0.0)
    dohl = np.stack([dopen, dhigh, dlow], axis=-1)
    dohl_max = int(np.abs(dohl).max(initial=0))
    dclose_max = int(np.abs(dclose).max(initial=0))
    if dclose_max > _I16 or dohl_max > _I16:
        return None
    vol_i = np.where(mask, np.rint(v), 0).astype(np.int64)
    dop, dh, dl = dohl[..., 0], dohl[..., 1], dohl[..., 2]
    h_off = dh - np.maximum(dop, 0)
    l_off = np.minimum(dop, 0) - dl
    wick_ok = int(((np.abs(dop) <= 127) & (h_off >= 0) & (h_off <= 15)
                   & (l_off >= 0) & (l_off <= 15)).all())
    tight_ok = int(((dop >= -8) & (dop <= 7) & (h_off >= 0) & (h_off <= 3)
                    & (l_off >= 0) & (l_off <= 3)).all())
    stats = (dohl_max, dclose_max,
             int((vol_i % 100 == 0).all()), int(vol_i.max(initial=0)),
             wick_ok, tight_ok)
    base, dclose, dohl, volume, vol_scale = narrow_wire(
        (base_ct / inv).astype(np.float32),
        dclose.astype(np.int16), dohl.astype(np.int16),
        vol_i.astype(np.int32), stats, floor=floor)
    return WireBatch(base=base, dclose=dclose, dohl=dohl, volume=volume,
                     maskbits=pack_mask(mask), vol_scale=vol_scale)


@functools.partial(jax.jit, static_argnames=("tick",))
def decode(base, dclose, dohl, volume, maskbits, vol_scale,
           tick: float = TICK):
    """On-device unpacking -> ``(bars [..., T, 240, 5] f32, mask)``.

    Fuses into the factor graph: XLA keeps the int->f32 expansion in
    HBM-local registers instead of shipping wide floats over the wire.
    """
    # slot count from dohl's slot axis (every dohl mode keeps it),
    # NOT a module constant: the same decode graph serves every
    # registered session's layout (ISSUE 15), and at 240 the traced
    # jaxpr is unchanged — all branches below are static-shape
    n_slots = dohl.shape[-2]
    bits = (maskbits[..., None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    m = bits.reshape(maskbits.shape[:-1] + (maskbits.shape[-1] * 8,))
    if maskbits.shape[-1] * 8 != n_slots:  # static: pad-bit slice only
        m = m[..., :n_slots]               # when S % 8 != 0 (us_390)
    m = m.astype(bool)
    inv = jnp.float32(round(1.0 / tick))
    if dclose.shape[-1] == n_slots // 2 and n_slots % 2 == 0 \
            and dclose.shape[-1] != n_slots:  # int4-pair packing
        b = dclose.astype(jnp.int32)
        lo = ((b & 0xF) ^ 8) - 8          # even slots, sign-extended
        hi = (((b >> 4) & 0xF) ^ 8) - 8   # odd slots
        dc = jnp.stack([lo, hi], axis=-1) \
            .reshape(dclose.shape[:-1] + (n_slots,))
    else:
        dc = dclose.astype(jnp.int32)
    ct = jnp.round(base * inv).astype(jnp.int32)[..., None] \
        + jnp.cumsum(dc, axis=-1)
    if dohl.shape[-1] == 1:  # tight packing (see module docstring)
        b = dohl[..., 0].astype(jnp.int32)
        dop = ((b & 0xF) ^ 8) - 8  # sign-extend the int4 body delta
        ot = ct + dop
        ht = jnp.maximum(ct, ot) + ((b >> 4) & 0x3)
        lt = jnp.minimum(ct, ot) - (b >> 6)
    elif dohl.shape[-1] == 2:  # wick packing
        b0 = jax.lax.bitcast_convert_type(dohl[..., 0], jnp.int8) \
            .astype(jnp.int32)
        b1 = dohl[..., 1].astype(jnp.int32)
        ot = ct + b0
        ht = jnp.maximum(ct, ot) + (b1 >> 4)
        lt = jnp.minimum(ct, ot) - (b1 & 0xF)
    else:
        d = dohl.astype(jnp.int32)
        ot = ct + d[..., 0]
        ht = ct + d[..., 1]
        lt = ct + d[..., 2]
    close = ct.astype(jnp.float32) / inv
    open_ = ot.astype(jnp.float32) / inv
    high = ht.astype(jnp.float32) / inv
    low = lt.astype(jnp.float32) / inv
    if n_slots % 4 == 0 and volume.dtype == jnp.uint8 \
            and volume.shape[-1] == vol10_bytes(n_slots):
        # 10-bit packed (4 values/5 bytes)
        g = volume.reshape(volume.shape[:-1] + (n_slots // 4, 5)) \
            .astype(jnp.int32)
        b0, b1, b2, b3, b4 = (g[..., i] for i in range(5))
        vals = jnp.stack([b0 | ((b1 & 0x3) << 8),
                          (b1 >> 2) | ((b2 & 0xF) << 6),
                          (b2 >> 4) | ((b3 & 0x3F) << 4),
                          (b3 >> 6) | (b4 << 2)], axis=-1)
        vol_units = vals.reshape(volume.shape[:-1] + (n_slots,))
    else:
        vol_units = volume
    vol = vol_units.astype(jnp.float32) * vol_scale.astype(jnp.float32)
    zero = jnp.zeros_like(close)
    bars = jnp.stack(
        [jnp.where(m, f, zero) for f in (open_, high, low, close, vol)],
        axis=-1)
    return bars, m


def pack_arrays(arrays) -> tuple:
    """Concatenate host arrays into ONE uint8 buffer + a static spec.

    Over the attached-TPU tunnel every ``device_put``/ready-check is a
    round trip, so a batch that ships as one buffer instead of six (and
    returns one stacked tensor instead of 58 — see the pipeline) spends
    one RTT where the per-array path spends dozens. ``spec`` is a
    hashable ``((dtype, shape, byte_offset), ...)`` that travels as a
    static jit argument; :func:`unpack` slices + bitcasts on device.
    """
    spec, chunks, off = [], [], 0
    for a in arrays:
        a = np.asarray(a)
        spec.append((a.dtype.str, a.shape, off))
        b = a.reshape(-1).view(np.uint8)
        pad = (-(off + b.nbytes)) % 4
        chunks.append(b)
        if pad:
            chunks.append(np.zeros(pad, np.uint8))
        off += b.nbytes + pad
    buf = np.concatenate(chunks)
    tel = get_telemetry()
    tel.counter("wire.packed_buffers")
    tel.counter("wire.packed_bytes", buf.nbytes)
    return buf, tuple(spec)


def unpack(buf, spec):
    """Invert :func:`pack_arrays` on device (jit-traceable; ``spec``
    static). Slices are static-offset, so XLA fuses the bitcasts into
    the consuming graph."""
    out = []
    for dtype_str, shape, off in spec:
        dt = np.dtype(dtype_str)
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        raw = jax.lax.slice(buf, (off,), (off + n * dt.itemsize,))
        if dt.itemsize == 1:
            arr = jax.lax.bitcast_convert_type(raw, dt)
        else:
            arr = jax.lax.bitcast_convert_type(
                raw.reshape(n, dt.itemsize), dt)
        out.append(arr.reshape(shape))
    return tuple(out)


def shard_arrays(arrays, n_shards: int):
    """Split a batch's arrays into ``n_shards`` contiguous ticker
    blocks.

    Works on wire arrays (``WireBatch.arrays``) and on the raw
    fallback's ``(bars, mask_u8)`` alike: every array of rank >= 2
    carries tickers on axis 1 and splits there; scalars (``vol_scale``)
    replicate into every shard. The split happens AFTER the full-batch
    encode, so per-shard narrowing decisions cannot diverge — shard s's
    bytes are literally a slice of the single-device encoding, which is
    what makes the sharded resident scan's decode bitwise.

    The tickers extent must divide by ``n_shards`` (callers pad with
    masked lanes first — see ``pipeline._grid_batch``'s lcm bucket and
    ``bench.encode_year_sharded``).
    """
    arrays = [np.asarray(a) for a in arrays]
    for a in arrays:
        if a.ndim >= 2 and a.shape[1] % n_shards:
            raise ValueError(
                f"tickers extent {a.shape[1]} does not divide into "
                f"{n_shards} shards — pad the batch first")
    out = []
    for s in range(n_shards):
        parts = []
        for a in arrays:
            if a.ndim >= 2:
                t = a.shape[1] // n_shards
                parts.append(a[:, s * t:(s + 1) * t])
            else:
                parts.append(a)
        out.append(tuple(parts))
    return out


def pack_sharded(arrays, n_shards: int) -> tuple:
    """Pack a batch as ``n_shards`` per-shard single buffers, stacked
    ``[S, L]``, plus the (shared) per-shard spec.

    Each row is an independent :func:`pack_arrays` buffer of one ticker
    shard, so a ``NamedSharding`` over the S axis lands shard s's bytes
    on the device that owns tickers-shard s and the on-device
    :func:`unpack` needs no cross-shard addressing. The spec is
    identical across shards by construction (same dtypes, same
    per-shard extents) and travels as ONE static jit argument.
    """
    packs = [pack_arrays(parts) for parts in shard_arrays(arrays,
                                                          n_shards)]
    specs = {spec for _, spec in packs}
    if len(specs) != 1:  # cannot happen: equal extents + shared dtypes
        raise AssertionError(f"per-shard specs diverged: {specs}")
    return np.stack([buf for buf, _ in packs]), packs[0][1]


def shard_arrays_2d(arrays, d_shards: int, t_shards: int):
    """Split a batch's arrays into a ``d_shards x t_shards`` grid of
    contiguous (day-span, ticker-block) tiles (ISSUE 13).

    Same contract as :func:`shard_arrays`, extended to the days axis:
    every array of rank >= 2 carries days on axis 0 and tickers on
    axis 1 and splits on BOTH; scalars (``vol_scale``) replicate into
    every tile. The split happens AFTER the full-batch encode, so
    per-tile narrowing decisions cannot diverge — tile (i, j)'s bytes
    are literally a 2-D slice of the single-device encoding, which is
    what keeps the 2-D resident scan's per-shard decode bitwise.

    Both extents must divide (callers pad tickers with masked lanes
    and days with fully-masked filler days first — see
    ``bench.encode_year_2d``). Returns ``grid[i][j]`` tuples.
    """
    arrays = [np.asarray(a) for a in arrays]
    for a in arrays:
        if a.ndim >= 2 and (a.shape[0] % d_shards
                            or a.shape[1] % t_shards):
            raise ValueError(
                f"batch extents {a.shape[:2]} do not divide into a "
                f"({d_shards}, {t_shards}) shard grid — pad the batch "
                "first")
    grid = []
    for i in range(d_shards):
        row = []
        for j in range(t_shards):
            parts = []
            for a in arrays:
                if a.ndim >= 2:
                    dd = a.shape[0] // d_shards
                    tt = a.shape[1] // t_shards
                    parts.append(a[i * dd:(i + 1) * dd,
                                   j * tt:(j + 1) * tt])
                else:
                    parts.append(a)
            row.append(tuple(parts))
        grid.append(row)
    return grid


def pack_sharded_2d(arrays, d_shards: int, t_shards: int) -> tuple:
    """Pack a batch as a ``[Sd, St, L]`` stack of per-tile single
    buffers plus the (shared) per-tile spec — the 2-D twin of
    :func:`pack_sharded`. A ``NamedSharding`` over the leading two
    axes (``parallel.mesh.packed_year_2d_spec``) lands tile (i, j)'s
    bytes on the device owning day-shard i x tickers-shard j, and the
    on-device :func:`unpack` needs no cross-shard addressing. The spec
    is identical across tiles by construction (equal extents, shared
    dtypes) and travels as ONE static jit argument."""
    grid = [[pack_arrays(cell) for cell in row]
            for row in shard_arrays_2d(arrays, d_shards, t_shards)]
    specs = {spec for row in grid for _, spec in row}
    if len(specs) != 1:  # cannot happen: equal extents + shared dtypes
        raise AssertionError(f"per-tile specs diverged: {specs}")
    return (np.stack([np.stack([buf for buf, _ in row])
                      for row in grid]),
            grid[0][0][1])


def put(wire: WireBatch, shardings=None):
    """device_put the packed representation (decode happens device-side)."""
    if shardings is None:
        return tuple(jax.device_put(a) for a in wire.arrays)
    return tuple(jax.device_put(a, s) for a, s in zip(wire.arrays, shardings))


def mesh_shardings(mesh):
    """NamedShardings placing a wire batch on a ``(days, tickers)`` mesh:
    every per-ticker array shards along the tickers axis (the wide,
    communication-free one), the vol_scale scalar replicates. The caller
    must pad the ticker axis to a multiple of the tickers mesh dim."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import TICKERS_AXIS

    t = TICKERS_AXIS
    return (NamedSharding(mesh, P(None, t)),              # base [D, T]
            NamedSharding(mesh, P(None, t, None)),        # dclose
            NamedSharding(mesh, P(None, t, None, None)),  # dohl
            NamedSharding(mesh, P(None, t, None)),        # volume
            NamedSharding(mesh, P(None, t, None)),        # maskbits
            NamedSharding(mesh, P()))                     # vol_scale
