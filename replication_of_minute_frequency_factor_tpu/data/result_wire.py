"""On-device blocked-quantized RESULT wire (the device->host leg).

:mod:`.wire` compressed the ingest direction to ~2.9 bytes/bar; the
result direction still ships the raw f32 ``[F, D, T]`` exposure block
(~9.3 MB per 8-day x 5000-ticker batch) over a tunnel that does
3-15 MB/s up — and docs/BENCHMARKS.md "Narrow result dtype" measured
and REJECTED uniform dtype narrowing (f16 overflows 22,355 lanes, bf16's
step exceeds parity rtol). This module is the blocked alternative: a
**per-(factor, day) affine int16 quantization** computed ON DEVICE as
the final fused stage of the producing graph, with a **per-slice
widening fallback to bitwise raw f32** chosen on device by a round-trip
error check — the ingest wire's widen-don't-reject contract, symmetric
on the output side.

Why per-(factor, day) blocks are the right unit: one slice IS one
cross-section — exactly what every downstream consumer (IC, rank-IC,
qcut deciles, top-k) operates on. An affine map per cross-section
preserves ordering up to quantization ties, and the guaranteed error is
**range-relative**: ``|decode(q) - x| <= (hi - lo) / 131068`` (half the
int16 step), which is the natural error measure for correlation- and
rank-shaped consumers. Factors whose consumers need VALUE-relative
accuracy carry tighter pinned bounds (``RESULT_BOUNDS``,
docs/PIN_BOUNDS.md "Result-wire bounds") and their heavy-tailed slices
widen instead.

Payload layout for one ``[F, D, T]`` block (packed into ONE uint8
buffer with :func:`..data.wire.pack_arrays`'s spec machinery, so the
consolidated per-group fetch stays one RTT):

  q       [F, D, T] int16  quantized lanes; NaN lanes ship the
                           ``Q_NAN`` sentinel (-32768) and decode to
                           NaN — NaN STATUS is preserved exactly
  scale   [F, D]    f32    per-slice step ((hi - lo) / 65534; 1.0 for
                           degenerate hi == lo slices, which decode
                           bit-exactly to ``offset``)
  offset  [F, D]    f32    per-slice lo
  sidx    [F, D]    int16  -1 = quantized; >= 0 = row in ``spill``
                           holding this slice's bitwise f32 lanes;
                           -2 = widened but the spill budget was full
                           (OVERFLOW — strict decode raises)
  spill   [S, T]    f32    raw rows for widened slices, in flat
                           (f, d) order of widening

``S`` (the spill budget) is static per executable; the host threads a
widen-only floor across runs exactly like the ingest wire's dtype
floor: an overflow bumps the budget and the next executable has room
(:class:`ResultWireSpec.grow`). Decode is a cheap host-side numpy
dequantize (:func:`decode_block`) — this module's ONLY host-side numpy
is there, and it deliberately avoids implicit device syncs (GL-A3
scope: the module is device-hot; callers hand decode an already-fetched
host buffer).

The on-device round-trip check is load-bearing, not decorative: beyond
heavy-tailed pinned factors it catches offset-dominated slices (values
like 1e9 +/- 1e-3, where f32 cannot even REPRESENT the dequantized
resolution — ``x' = q * scale + offset`` rounds at ulp(offset)), slices
containing +/-inf, and non-finite scales; all of those widen to bitwise
f32 rather than shipping silently-degraded lanes.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: int16 NaN sentinel (decodes to NaN; never produced by quantization)
Q_NAN = -32768
#: quantized lanes land in [-Q_LIM, Q_LIM]
Q_LIM = 32767
#: number of representable quantization steps
Q_STEPS = 2 * Q_LIM  # 65534

#: sidx markers
SIDX_QUANTIZED = -1
SIDX_OVERFLOW = -2

#: default pinned bound: range-relative absolute error. The int16
#: quantization GUARANTEES (hi - lo) / 131068 ~= 7.63e-6 x range, so
#: 1e-5 holds with ~1.3x margin over the worst case plus fp evaluation
#: wobble; a slice that cannot meet it (offset-dominated, inf-bearing)
#: widens.
DEFAULT_ATOL_REL = 1e-5
DEFAULT_RTOL = 0.0

#: per-factor pinned bounds (docs/PIN_BOUNDS.md "Result-wire bounds"):
#: ``(rtol, atol_rel, force_widen)``. The STRICT class pins factors
#: whose magnitudes are CNY-volume/amount-scaled (the f16-overflow set
#: of benchmarks/result_dtype_check.py) or value-relative by
#: consumption: their bound is PURELY ``rtol * |x|`` (atol_rel = 0 —
#: any range-relative slack would swallow exactly the tiny-lane errors
#: the pin exists to catch), so a heavy-tailed slice (values spanning
#: more than ~rtol * Q_STEPS decades, i.e. tiny lanes sharing a slice
#: with huge ones) fails the on-device check and ships bitwise f32
#: instead of range-relative noise.
_STRICT = (2e-3, 0.0, False)
RESULT_BOUNDS: Dict[str, Tuple[float, float, bool]] = {
    "vol_volume1min": _STRICT,
    "vol_upVol": _STRICT,
    "vol_downVol": _STRICT,
    "liq_amihud_1min": _STRICT,
    "liq_openvol": _STRICT,
    "liq_closevol": _STRICT,
    "liq_closeprevol": _STRICT,
    "shape_skewVol": _STRICT,
    "shape_kurtVol": _STRICT,
}


def factor_bounds(name: str) -> Tuple[float, float, bool]:
    """Pinned ``(rtol, atol_rel, force_widen)`` for one factor."""
    return RESULT_BOUNDS.get(name, (DEFAULT_RTOL, DEFAULT_ATOL_REL,
                                    False))


class ResultWireOverflow(RuntimeError):
    """More slices widened than the executable's static spill budget —
    the payload is marked (``sidx == -2``) rather than silently lossy.
    Callers grow the widen-only floor (:meth:`ResultWireSpec.grow`) and
    re-encode under a bigger budget, mirroring the ingest wire's
    re-encode-until-converged loop (bench.encode_year)."""


@dataclasses.dataclass(frozen=True)
class ResultWireSpec:
    """Static (hashable) encode spec: one per compiled executable.

    ``bounds[f]`` is factor f's pinned ``(rtol, atol_rel,
    force_widen)``; ``spill_rows`` is the static widen budget S. The
    spec travels as a static jit argument, so it is part of every AOT
    executable key — growing the floor compiles a fresh executable, as
    the contract requires."""
    bounds: Tuple[Tuple[float, float, bool], ...]
    spill_rows: int

    @classmethod
    def for_names(cls, names: Sequence[str],
                  spill_rows: Optional[int] = None,
                  days: int = 8) -> "ResultWireSpec":
        names = tuple(names)
        if spill_rows is None:
            spill_rows = default_spill_rows(len(names), days)
        return cls(bounds=tuple(factor_bounds(n) for n in names),
                   spill_rows=int(spill_rows))

    def grow(self, needed: int, headroom: float = 1.25
             ) -> "ResultWireSpec":
        """Widen-only floor bump: never shrinks."""
        rows = max(self.spill_rows, int(np.ceil(needed * headroom)))
        return dataclasses.replace(self, spill_rows=rows)


def default_spill_rows(n_factors: int, days: int) -> int:
    """Default static spill budget: ~2% of the block's slices (widening
    is the exception by construction — the default bound is guaranteed
    by the quantization itself), floored at 4 so tiny smokes always
    have room. At the headline shape (58 x 8 x 5000) this is 10 rows =
    0.2 MB against a 4.6 MB q plane."""
    return max(4, int(np.ceil(0.02 * n_factors * max(1, days))))


# --------------------------------------------------------------------------
# payload spec (host): mirrors wire.pack_arrays' layout math
# --------------------------------------------------------------------------


def payload_arrays_shapes(n_factors: int, days: int, tickers: int,
                          spill_rows: int):
    """``(dtype, shape)`` of the payload arrays, in pack order."""
    return (
        (np.dtype(np.int16), (n_factors, days, tickers)),    # q
        (np.dtype(np.float32), (n_factors, days)),           # scale
        (np.dtype(np.float32), (n_factors, days)),           # offset
        (np.dtype(np.int16), (n_factors, days)),             # sidx
        (np.dtype(np.float32), (spill_rows, tickers)),       # spill
    )


def payload_spec(n_factors: int, days: int, tickers: int,
                 spill_rows: int) -> tuple:
    """The exact ``((dtype_str, shape, byte_offset), ...)`` spec
    :func:`..data.wire.pack_arrays` would produce for the payload
    arrays — asserted equal in tests, so the two layouts cannot
    drift. 4-byte alignment pads between chunks, like pack_arrays."""
    spec, off = [], 0
    for dt, shape in payload_arrays_shapes(n_factors, days, tickers,
                                           spill_rows):
        spec.append((dt.str, shape, off))
        nbytes = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        off += nbytes + ((-(off + nbytes)) % 4)
    return tuple(spec)


def payload_nbytes(n_factors: int, days: int, tickers: int,
                   spill_rows: int) -> int:
    """Total packed payload length in bytes (the device buffer's L)."""
    last_dt, last_shape, last_off = payload_spec(
        n_factors, days, tickers, spill_rows)[-1]
    nbytes = (int(np.prod(last_shape, dtype=np.int64))
              * np.dtype(last_dt).itemsize)
    end = last_off + nbytes
    return end + ((-end) % 4)


# --------------------------------------------------------------------------
# device encode (pure jax — fused into the producing graph)
# --------------------------------------------------------------------------


def _pack_device(arrays) -> jnp.ndarray:
    """Device twin of ``wire.pack_arrays``: bitcast each array to bytes
    and concatenate into one flat uint8 buffer with the SAME 4-byte
    alignment, so the host unpacks with the shared spec machinery."""
    chunks = []
    off = 0
    for a in arrays:
        if a.dtype.itemsize == 1:
            b = a.reshape(-1)
        else:
            b = jax.lax.bitcast_convert_type(
                a.reshape(-1), jnp.uint8).reshape(-1)
        nbytes = b.shape[0]
        pad = (-(off + nbytes)) % 4
        chunks.append(b)
        if pad:
            chunks.append(jnp.zeros((pad,), jnp.uint8))
        off += nbytes + pad
    return jnp.concatenate(chunks)


def encode_block(x: jnp.ndarray, spec: ResultWireSpec) -> jnp.ndarray:
    """Quantize one ``[F, D, T]`` exposure block on device into the
    packed ``[L] uint8`` payload (see module docstring for the layout).

    Per (factor, day) slice: masked min/max -> affine int16 with the
    NaN sentinel -> round-trip error check against the factor's pinned
    bound -> widen (ship bitwise f32 via the spill plane) on failure.
    Pure jax, zero while/scan, zero callbacks, f32-only — traced by
    graftlint under the reserved ``__result_encode__`` symbol."""
    f, d, t = x.shape
    if len(spec.bounds) != f:
        raise ValueError(f"spec pins {len(spec.bounds)} factors; block "
                         f"has {f}")
    finite = jnp.isfinite(x)
    has_finite = jnp.any(finite, axis=-1)                     # [F, D]
    big = jnp.float32(np.finfo(np.float32).max)
    lo = jnp.min(jnp.where(finite, x, big), axis=-1)
    hi = jnp.max(jnp.where(finite, x, -big), axis=-1)
    lo = jnp.where(has_finite, lo, 0.0)
    hi = jnp.where(has_finite, hi, 0.0)
    rng = hi - lo
    degenerate = rng <= 0.0
    scale = jnp.where(degenerate, 1.0, rng / jnp.float32(Q_STEPS))
    offset = lo
    qf = jnp.round((x - offset[..., None]) / scale[..., None])
    q = jnp.clip(qf - jnp.float32(Q_LIM), -Q_LIM, Q_LIM)
    q = jnp.where(finite, q, jnp.float32(Q_NAN)).astype(jnp.int16)
    # round-trip check, exactly the host dequantize expression
    xr = ((q.astype(jnp.float32) + jnp.float32(Q_LIM))
          * scale[..., None] + offset[..., None])
    err = jnp.abs(xr - x)
    rtol = jnp.asarray([b[0] for b in spec.bounds],
                       jnp.float32)[:, None, None]
    atol_rel = jnp.asarray([b[1] for b in spec.bounds],
                           jnp.float32)[:, None, None]
    force = jnp.asarray([b[2] for b in spec.bounds],
                        jnp.bool_)[:, None]
    bound = atol_rel * rng[..., None] + rtol * jnp.abs(x)
    lane_bad = finite & ~(err <= bound)
    widen = (jnp.any(lane_bad, axis=-1)
             | jnp.any(jnp.isinf(x), axis=-1)
             | ~jnp.isfinite(scale)
             | force)                                         # [F, D]
    wflat = widen.reshape(-1)
    row = jnp.cumsum(wflat.astype(jnp.int32)) - 1             # [F*D]
    fits = wflat & (row < spec.spill_rows)
    sidx = jnp.where(wflat,
                     jnp.where(fits, row, SIDX_OVERFLOW),
                     SIDX_QUANTIZED).reshape(f, d).astype(jnp.int16)
    # scatter widened slices' raw f32 rows; out-of-budget rows drop
    # (their sidx already says OVERFLOW)
    target = jnp.where(fits, row, spec.spill_rows)            # [F*D]
    spill = jnp.zeros((spec.spill_rows, t), jnp.float32)
    spill = spill.at[target].set(x.reshape(-1, t), mode="drop")
    return _pack_device((q, scale, offset, sidx, spill))


def encode_stacked(x: jnp.ndarray, spec: ResultWireSpec) -> jnp.ndarray:
    """``[N, F, D, T]`` -> ``[N, L]``: vmapped :func:`encode_block` for
    the sharded resident path, where the encode must sit OUTSIDE the
    ``shard_map`` (per-slice min/max is a cross-ticker — i.e.
    cross-shard — reduction; GSPMD partitions it, and the global
    parameters keep sharded payloads bit-comparable with the
    single-device encode)."""
    return jax.vmap(lambda b: encode_block(b, spec))(x)


# --------------------------------------------------------------------------
# host decode (numpy; input is an ALREADY-FETCHED host buffer)
# --------------------------------------------------------------------------


def _unpack_host(buf: np.ndarray, spec: tuple):
    out = []
    flat = buf.reshape(-1).view(np.uint8)
    for dtype_str, shape, off in spec:
        dt = np.dtype(dtype_str)
        n = int(np.prod(shape, dtype=np.int64))
        out.append(flat[off:off + n * dt.itemsize].view(dt)
                   .reshape(shape))
    return out


def decode_block(buf: np.ndarray, n_factors: int, days: int,
                 tickers: int, spill_rows: int, strict: bool = True,
                 telemetry=None, names: Optional[Sequence[str]] = None):
    """Dequantize one fetched payload back to ``([F, D, T] f32,
    verdict)``.

    Widened slices come back BITWISE (the spill rows are the raw f32
    lanes); quantized slices carry the pinned range-relative error; NaN
    lanes are NaN. ``verdict`` reports ``{quantized, widened, overflow,
    payload_bytes, f32_bytes, ratio}``; ``strict`` raises
    :class:`ResultWireOverflow` when any slice overflowed the spill
    budget (the caller's cue to grow the floor).

    ``names`` (ISSUE 12) attributes the widen disposition PER FACTOR:
    the verdict gains ``widened_by_factor`` (nonzero counts only) and
    each factor's count lands in the ``result.widen_count{factor=}``
    counter — the instrument behind the ROADMAP's open question (how
    often do the strict-pinned volume factors widen on real data); the
    spill-plane occupancy gauge ``result.spill_occupancy_frac``
    (widened / budget) says how close the static budget is to its next
    growth."""
    spec = payload_spec(n_factors, days, tickers, spill_rows)
    q, scale, offset, sidx, spill = _unpack_host(buf, spec)
    out = ((q.astype(np.float32) + np.float32(Q_LIM))
           * scale[..., None] + offset[..., None])
    out[q == Q_NAN] = np.nan
    widened = sidx >= 0
    if widened.any():
        out[widened] = spill[sidx[widened].astype(np.int64)]
    n_overflow = int((sidx == SIDX_OVERFLOW).sum())
    payload_bytes = int(buf.nbytes)  # buf is an already-fetched host
    # array — decode never touches the device (GL-A3: this module is
    # device-hot scope; the fetch is the caller's declared boundary)
    f32_bytes = n_factors * days * tickers * 4
    verdict = {
        "quantized": int((sidx == SIDX_QUANTIZED).sum()),
        "widened": int(widened.sum()),
        "overflow": n_overflow,
        "payload_bytes": payload_bytes,
        "f32_bytes": f32_bytes,
        "ratio": round(f32_bytes / payload_bytes, 3)
        if payload_bytes else None,
        # the per-slice disposition plane, for parity gates
        # (check_bounds); NOT JSON-able — record stampers drop it
        "sidx": sidx,
    }
    tel = telemetry
    if tel is None:
        from ..telemetry import get_telemetry
        tel = get_telemetry()
    tel.counter("result.decode_blocks")
    tel.counter("result.bytes", payload_bytes)
    tel.counter("result.widened_slices", verdict["widened"])
    if names is not None:
        if len(names) != n_factors:
            raise ValueError(f"names has {len(names)} entries; payload "
                             f"holds {n_factors} factors")
        # widened OR overflowed slices both failed the round-trip
        # check — the per-factor widen counters count the data truth,
        # not what fit the spill budget
        per_factor = ((sidx != SIDX_QUANTIZED).sum(axis=1)
                      .astype(np.int64))
        by_factor = {}
        for n, c in zip(names, per_factor):
            if c:
                tel.counter("result.widen_count", int(c),
                            factor=str(n))
                by_factor[str(n)] = int(c)
        verdict["widened_by_factor"] = by_factor
        if spill_rows > 0:
            tel.gauge("result.spill_occupancy_frac",
                      round(verdict["widened"] / spill_rows, 6))
    if n_overflow:
        tel.counter("result.overflow_slices", n_overflow)
    if strict and n_overflow:
        raise ResultWireOverflow(
            f"{n_overflow} widened slice(s) did not fit the {spill_rows}"
            f"-row spill budget; grow the widen-only floor "
            f"(ResultWireSpec.grow) and re-encode")
    return out, verdict


def check_bounds(raw: np.ndarray, decoded: np.ndarray,
                 names: Sequence[str], sidx: Optional[np.ndarray] = None
                 ) -> dict:
    """Parity gate helper: verify ``decoded`` against the raw f32 block
    under the pinned per-factor contract — BITWISE where widened,
    within ``atol_rel * range + rtol * |x|`` where quantized, NaN
    status everywhere. Returns ``{ok, bad_factors, max_rel_err}``."""
    bad, max_rel = [], 0.0
    for i, n in enumerate(names):
        a, b = raw[i], decoded[i]
        if not np.array_equal(np.isnan(a), np.isnan(b)):
            bad.append(n)
            continue
        finite = np.isfinite(a)
        if not np.array_equal(finite, np.isfinite(b)):
            bad.append(n)
            continue
        rtol, atol_rel, _ = factor_bounds(n)
        for d in range(a.shape[0]):
            af, bf = a[d], b[d]
            fin = np.isfinite(af)
            if sidx is not None and sidx[i, d] >= 0:
                # widened slice: bitwise, nothing else to check
                if not np.array_equal(af[fin], bf[fin]):
                    bad.append(n)
                continue
            if not fin.any():
                continue
            lo, hi = af[fin].min(), af[fin].max()
            bound = atol_rel * (hi - lo) + rtol * np.abs(af[fin])
            err = np.abs(bf[fin] - af[fin])
            # widened slices are bitwise, which trivially satisfies any
            # bound; quantized slices must fit the pinned one
            if not (err <= np.maximum(bound, 0.0)).all():
                bad.append(n)
            scale_ref = max(abs(lo), abs(hi), 1e-30)
            max_rel = max(max_rel, float(err.max(initial=0.0))
                          / scale_ref)
    return {"ok": not bad, "bad_factors": sorted(set(bad)),
            "max_rel_err": max_rel}


# --------------------------------------------------------------------------
# wire framing (ISSUE 20): the HTTP leg of the result wire
# --------------------------------------------------------------------------

#: frame magic: "Minute Factor Wire", layout version 1
FRAME_MAGIC = b"MFW1"
FRAME_VERSION = 1

#: fixed-size frame header preceding each packed payload on the HTTP
#: leg: magic, version, flags (reserved 0), n_factors, days, tickers,
#: spill_rows, start, end (the day-range the payload answers; signed so
#: a rangeless intraday frame can carry -1), payload_len
_FRAME_HEADER = struct.Struct("<4sHHIIIIiiI")
FRAME_HEADER_BYTES = _FRAME_HEADER.size


def pack_frame(payload, *, n_factors: int, days: int, tickers: int,
               spill_rows: int, start: int = 0, end: int = 0) -> bytes:
    """One self-describing wire frame: header + the packed payload
    VERBATIM (the buffer :func:`encode_block` produced, already fetched
    to host — framing is pure host-side byte shuffling, never a device
    sync). A buffered ``/v1/query`` wire answer is one frame; a chunked
    range answer is one frame per (block, day-range) chunk, each
    independently decodable because the header carries the full
    geometry and quantization is per-(factor, day) slice."""
    body = payload.tobytes() if hasattr(payload, "tobytes") \
        else bytes(payload)
    expect = payload_nbytes(n_factors, days, tickers, spill_rows)
    if len(body) != expect:
        raise ValueError(
            f"payload is {len(body)} bytes; the "
            f"[{n_factors}, {days}, {tickers}] + {spill_rows}-row "
            f"spill geometry packs to {expect}")
    head = _FRAME_HEADER.pack(FRAME_MAGIC, FRAME_VERSION, 0,
                              n_factors, days, tickers, spill_rows,
                              start, end, len(body))
    return head + body


def unpack_frame(buf, offset: int = 0) -> Tuple[dict, np.ndarray, int]:
    """Parse ONE frame at ``offset`` -> ``(meta, payload, next_offset)``
    where ``meta`` has the header fields and ``payload`` is the packed
    uint8 buffer ready for :func:`decode_block`. Raises ``ValueError``
    on a bad magic, an unknown version, or a truncated buffer — the
    malformed-wire contract the edge robustness tests exercise."""
    view = memoryview(buf)
    if len(view) - offset < FRAME_HEADER_BYTES:
        raise ValueError(
            f"truncated result-wire frame: {len(view) - offset} bytes "
            f"at offset {offset}; the header alone is "
            f"{FRAME_HEADER_BYTES}")
    (magic, version, _flags, n_factors, days, tickers, spill_rows,
     start, end, payload_len) = _FRAME_HEADER.unpack_from(view, offset)
    if magic != FRAME_MAGIC:
        raise ValueError(f"bad result-wire frame magic {bytes(magic)!r}"
                         f" (want {FRAME_MAGIC!r})")
    if version != FRAME_VERSION:
        raise ValueError(f"unknown result-wire frame version {version}")
    expect = payload_nbytes(n_factors, days, tickers, spill_rows)
    if payload_len != expect:
        raise ValueError(
            f"frame header claims {payload_len} payload bytes; the "
            f"[{n_factors}, {days}, {tickers}] + {spill_rows}-row "
            f"geometry packs to {expect}")
    body_off = offset + FRAME_HEADER_BYTES
    if len(view) - body_off < payload_len:
        raise ValueError(
            f"truncated result-wire frame: payload wants {payload_len} "
            f"bytes, buffer holds {len(view) - body_off}")
    payload = np.frombuffer(view, np.uint8, count=payload_len,
                            offset=body_off)
    meta = {"version": version, "n_factors": n_factors, "days": days,
            "tickers": tickers, "spill_rows": spill_rows,
            "start": start, "end": end, "payload_bytes": payload_len}
    return meta, payload, body_off + payload_len


def iter_frames(buf):
    """Yield every ``(meta, payload)`` frame in ``buf`` in order.
    Trailing garbage (a partial frame) raises like :func:`unpack_frame`
    — a reassembled chunked response must be EXACTLY a frame
    sequence."""
    offset, n = 0, len(memoryview(buf))
    while offset < n:
        meta, payload, offset = unpack_frame(buf, offset)
        yield meta, payload
