"""L0 data plane: parquet/long-format minute bars -> dense day tensors."""

from .minute import DayGrid, FIELDS, grid_day, F_OPEN, F_HIGH, F_LOW, F_CLOSE, F_VOLUME  # noqa: F401
from .synthetic import synth_day  # noqa: F401
