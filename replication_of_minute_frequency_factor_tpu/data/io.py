"""Parquet IO: day-file discovery, column loading, atomic writes.

Reproduces the reference's on-disk contracts (SURVEY.md §2.3):

* one minute-bar parquet per trading day, date = first 8 filename chars
  parsed ``%Y%m%d`` (MinuteFrequentFactorCICC.py:69-77);
* exposure parquet written atomically via tempfile-then-rename
  (Factor.py:74-90) so a crash mid-write never corrupts the cache;
* daily price/volume parquet with CSMAR column names renamed on load
  (Factor.py:32-47).

pyarrow replaces polars as the host-side columnar engine; everything
numeric leaves here as numpy, bound for the device.
"""

from __future__ import annotations

import os
import re
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from ..telemetry import get_telemetry

_DATE_RE = re.compile(r"^(\d{8})")

#: CSMAR -> canonical column renames (reference Factor.py:32-47)
DAILY_PV_RENAME = {
    "Trddt": "date",
    "Stkcd": "code",
    "Opnprc": "open",
    "Hiprc": "high",
    "Loprc": "low",
    "Clsprc": "close",
    "Dnshrtrd": "volume",
    "Dnvaltrd": "amount",
    "ChangeRatio": "pct_change",
    "Dsmvosd": "cmc",
    "Dsmvtll": "tmc",
    "Adjprcwd": "close_adjust",
    "LimitDown": "limit_down",
    "LimitUp": "limit_up",
}


def parse_day_filename(name: str) -> Optional[np.datetime64]:
    """``'20240102_clean.parquet'`` -> 2024-01-02; None if no date prefix."""
    m = _DATE_RE.match(os.path.basename(name))
    if not m:
        return None
    s = m.group(1)
    try:
        return np.datetime64(f"{s[:4]}-{s[4:6]}-{s[6:8]}", "D")
    except ValueError:
        return None


def list_day_files(minute_dir: str) -> List[Tuple[np.datetime64, str]]:
    """Date-sorted ``(date, path)`` for every parquet day file in a dir."""
    out = []
    for name in os.listdir(minute_dir):
        if not name.endswith(".parquet"):
            continue
        date = parse_day_filename(name)
        if date is not None:
            out.append((date, os.path.join(minute_dir, name)))
    out.sort(key=lambda t: t[0])
    return out


def read_columns(path: str,
                 columns: Sequence[str]) -> Dict[str, np.ndarray]:
    """Read selected parquet columns as a dict of numpy arrays."""
    table = pq.read_table(path, columns=list(columns))
    out = {}
    for name in columns:
        col = table.column(name)
        if pa.types.is_string(col.type) or pa.types.is_large_string(col.type):
            out[name] = np.asarray(col.to_pylist())
        else:
            out[name] = col.to_numpy(zero_copy_only=False)
    return out

MINUTE_COLUMNS = ("code", "time", "open", "high", "low", "close", "volume")


def int_codes_to_str(code: np.ndarray) -> np.ndarray:
    """Integer stock codes -> zero-padded 6-char strings, vectorized.

    ``np.char.zfill(arr.astype(str), 6)`` walks per-element fixed-up
    strings and cost ~0.64 s per 1.2M-row day file — a real slice of the
    pipeline's producer budget. The shift trick (add 10^6, format via
    the C-level ``astype('U7')``, slice off the leading '1' through a
    'U1' view) is bit-identical and ~3x faster (measured 0.21 s).
    Codes outside [0, 999999] can't take the trick (a 7-digit code must
    keep all its digits) and fall back to a per-element zfill —
    np.char.zfill is NOT safe there: on numpy 2.x it allocates U6 and
    silently TRUNCATES a 7-digit code ('1000000' -> '100000'), which
    would merge two tickers onto one axis entry downstream."""
    code = np.asarray(code)
    if code.size == 0:
        return code.astype("U6")
    if code.min() < 0 or code.max() > 999_999:
        return np.array([str(c).zfill(6) for c in code.tolist()])
    s = (code.astype(np.int64) + 1_000_000).astype("U7")
    return np.ascontiguousarray(
        s.view("U1").reshape(len(s), 7)[:, 1:]).view("U6").ravel()


def read_minute_day(path: str) -> Dict[str, np.ndarray]:
    """One day file's columns; integer stock codes are zero-padded to the
    6-char string form, matching read_daily_pv — CSMAR exports carry
    codes as either, and without one normalization an int-coded minute
    file would join the daily PV table ('000002') as '2', silently
    producing an empty evaluation."""
    out = read_minute_day_raw(path)
    if out["code"].dtype.kind in "iu":
        out["code"] = int_codes_to_str(out["code"])
    return out


def read_minute_day_raw(path: str) -> Dict[str, np.ndarray]:
    """Like :func:`read_minute_day` but WITHOUT code normalization:
    integer code columns come back as int64. The device pipeline's grid
    path keeps integer codes integer until the 5000-element ticker axis
    is rendered once per batch (pipeline._grid_batch) — normalizing the
    1.2M-row column per day costs ~0.2 s that the axis-level render
    avoids. Callers that JOIN on codes (evaluation, the oracle/polars
    backends) must use the normalizing reader."""
    tel = get_telemetry()
    tel.counter("io.day_files_read")
    try:
        tel.counter("io.bytes_read", os.path.getsize(path))
    except OSError:
        pass  # path may be unreadable; the read below raises properly
    return read_columns(path, MINUTE_COLUMNS)


#: frame header magic + codec ids for :func:`frame_bytes` (ISSUE 10:
#: the on-disk half of the wire program — the exposure cache's framed
#: format). The codec CHAIN is graceful: zstd when the ``zstandard``
#: module is installed, else LZ4 (``lz4.frame``), else the stdlib
#: ``zlib`` — this container has neither wheel, so zlib is the live
#: default and the zstd/lz4 branches light up wherever the wheels
#: exist. Every encode/decode lands in ``io.frame_codec{kind=...}``.
FRAME_MAGIC = b"MFFZ"
_FRAME_CODECS = ("zstd", "lz4", "zlib")


def _codec_module(kind: str):
    import importlib
    try:
        if kind == "zstd":
            return importlib.import_module("zstandard")
        if kind == "lz4":
            return importlib.import_module("lz4.frame")
        import zlib
        return zlib
    except ImportError:
        return None


def pick_frame_codec() -> str:
    """First available codec in the zstd -> lz4 -> zlib chain (zlib is
    stdlib, so there is always one)."""
    for kind in _FRAME_CODECS:
        if _codec_module(kind) is not None:
            return kind
    return "zlib"  # unreachable: zlib is stdlib


def frame_bytes(data: bytes, codec: str = "auto") -> bytes:
    """Compress ``data`` into a self-describing frame:
    ``MFFZ | codec id (1B) | raw length (8B LE) | payload``."""
    kind = pick_frame_codec() if codec == "auto" else codec
    mod = _codec_module(kind)
    if mod is None:
        raise ValueError(f"frame codec {kind!r} is not available "
                         f"(chain: {_FRAME_CODECS})")
    if kind == "zstd":
        payload = mod.ZstdCompressor().compress(data)
    elif kind == "lz4":
        payload = mod.compress(data)
    else:
        payload = mod.compress(data, 6)
    get_telemetry().counter("io.frame_codec", kind=kind, op="encode")
    return (FRAME_MAGIC + bytes([_FRAME_CODECS.index(kind)])
            + len(data).to_bytes(8, "little") + payload)


def unframe_bytes(blob: bytes) -> bytes:
    """Invert :func:`frame_bytes`; raises with the codec name when the
    frame needs a module this host lacks."""
    if blob[:4] != FRAME_MAGIC:
        raise ValueError("not an MFFZ frame (bad magic)")
    kind = _FRAME_CODECS[blob[4]]
    raw_len = int.from_bytes(blob[5:13], "little")
    mod = _codec_module(kind)
    if mod is None:
        raise ValueError(
            f"frame was written with {kind!r}, which is not installed "
            "here; install it or rewrite the cache with codec='zlib'")
    if kind == "zstd":
        out = mod.ZstdDecompressor().decompress(blob[13:],
                                                max_output_size=raw_len)
    elif kind == "lz4":
        out = mod.decompress(blob[13:])
    else:
        out = mod.decompress(blob[13:])
    if len(out) != raw_len:
        raise ValueError(f"frame decoded to {len(out)} bytes; header "
                         f"promised {raw_len}")
    get_telemetry().counter("io.frame_codec", kind=kind, op="decode")
    return out


def write_framed_table_atomic(table: pa.Table, path: str,
                              codec: str = "auto") -> None:
    """Arrow-IPC-serialize ``table`` and write it as one compressed
    frame, atomically (tempfile-then-rename, like the parquet twin) —
    the exposure cache's ``.mffz`` format."""
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    blob = frame_bytes(sink.getvalue().to_pybytes(), codec=codec)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".mffz.tmp", dir=d)
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(blob)
        os.replace(tmp, path)
        tel = get_telemetry()
        tel.counter("io.framed_writes")
        tel.counter("io.bytes_written", len(blob))
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


def read_framed_table(path: str) -> pa.Table:
    with open(path, "rb") as fh:
        blob = fh.read()
    with pa.ipc.open_stream(pa.BufferReader(unframe_bytes(blob))) as r:
        return r.read_all()


def _parquet_codec() -> str:
    """pyarrow-side codec pick for the parquet cache: zstd -> lz4 ->
    snappy (pyarrow's own default), whichever this build carries."""
    for kind in ("zstd", "lz4", "snappy"):
        try:
            if pa.Codec.is_available(kind):
                return kind
        except Exception:  # noqa: BLE001 — fall through the chain
            continue
    return "snappy"


def write_parquet_atomic(table: pa.Table, path: str,
                         compression: str = "auto") -> None:
    """tempfile-in-target-dir -> fsync-free rename; temp removed on failure
    (the reference's crash-safety mechanism, Factor.py:74-90).
    ``compression='auto'`` picks the best codec this pyarrow build
    carries (zstd -> lz4 -> snappy) and counts the choice in
    ``io.parquet_codec{kind=...}`` — the exposure-cache half of the
    ISSUE 10 bytes program."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    codec = _parquet_codec() if compression == "auto" else compression
    fd, tmp = tempfile.mkstemp(suffix=".parquet.tmp", dir=d)
    os.close(fd)
    try:
        pq.write_table(table, tmp, compression=codec)
        nbytes = os.path.getsize(tmp)
        os.replace(tmp, path)
        tel = get_telemetry()
        tel.counter("io.parquet_writes")
        tel.counter("io.parquet_codec", kind=codec)
        tel.counter("io.bytes_written", nbytes)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


def coerce_dates(dates: np.ndarray) -> np.ndarray:
    """To datetime64[D], accepting ISO strings and compact ``YYYYMMDD``
    (CSMAR exports use both). Raises on out-of-range results instead of
    letting numpy's year-only fallback turn ``"20240102"`` into the year
    20240102 — a silent empty join downstream otherwise."""
    dates = np.asarray(dates)
    if np.issubdtype(dates.dtype, np.datetime64):
        return dates.astype("datetime64[D]")
    if dates.dtype.kind in "iu":  # integer YYYYMMDD
        dates = dates.astype(str)
    if dates.dtype.kind == "S":  # bytes -> str (str(b'x') would mangle)
        dates = np.char.decode(dates, "utf-8")
    if dates.dtype.kind in "UO" and len(dates):
        stripped = np.char.strip(dates.astype(str))
        nonempty = stripped[stripped != ""]
        if len(nonempty) and len(nonempty[0]) == 8 and nonempty[0].isdigit():
            dates = np.array(
                [f"{x[:4]}-{x[4:6]}-{x[6:8]}"
                 if len(x) == 8 and x.isdigit() else "NaT"
                 for x in stripped])
    out = np.asarray(dates, dtype="datetime64[D]")
    ok = ~np.isnat(out)  # missing dates stay NaT (they drop from joins)
    if ok.any():
        years = out[ok].astype("datetime64[Y]").astype(int) + 1970
        if years.min() < 1900 or years.max() > 2200:
            raise ValueError(
                f"unparseable trading dates (years {years.min()}-"
                f"{years.max()}): expected ISO YYYY-MM-DD or compact "
                "YYYYMMDD strings")
    return out


def read_stock_pool(path: str, pool: str,
                    dates: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Membership ``(codes, dates)`` pairs of an index stock pool.

    The reference only *advertises* index pools (hs300/zz500/zz1000 in the
    ``cal_final_exposure`` docstring) and raises for them (quirk Q9,
    MinuteFrequentFactorCICC.py:137-140); this is the working
    implementation behind ``Config.stock_pool_path``. Two schemas:

    * exact rows: columns ``code, date, pool`` — one row per member-day;
    * intervals (CSMAR constituent files): columns ``code, in_date,
      out_date, pool`` — member while ``in_date <= d < out_date``
      (null/NaT ``out_date`` = still a member), expanded onto the given
      trading ``dates``.

    ``pool`` selects rows by the ``pool`` column (absent column = the file
    is a single pool). Codes normalise to zero-padded 6-char strings.
    """
    names = pq.read_schema(path).names
    interval = "in_date" in names
    cols = ["code"] + (["in_date", "out_date"] if interval else ["date"])
    if "pool" in names:
        cols.append("pool")
    raw = read_columns(path, cols)
    code = np.asarray(raw["code"])
    if code.dtype.kind in "iu":
        code = int_codes_to_str(code)
    code = code.astype(object)
    keep = np.ones(len(code), bool)
    if "pool" in raw:
        pools = np.asarray(raw["pool"]).astype(str)
        keep = pools == pool
        if not keep.any():
            raise ValueError(
                f"stock pool {pool!r} matches no rows in {path}; "
                f"available pools: {sorted(set(pools))}")
    dates = np.sort(np.asarray(dates, "datetime64[D]"))
    if not interval:
        d = coerce_dates(raw["date"])[keep]
        return code[keep], d
    in_d = coerce_dates(raw["in_date"])[keep]
    out_d = coerce_dates(raw["out_date"])[keep]
    code = code[keep]
    far = np.datetime64("2200-01-01", "D")
    out_d = np.where(np.isnat(out_d), far, out_d)
    mcodes, mdates = [], []
    for c, lo, hi in zip(code, in_d, out_d):
        a = np.searchsorted(dates, lo, side="left")
        b = np.searchsorted(dates, hi, side="left")
        if b > a:
            mcodes.append(np.full(b - a, c, object))
            mdates.append(dates[a:b])
    if not mcodes:
        return (np.array([], object), np.array([], "datetime64[D]"))
    return np.concatenate(mcodes), np.concatenate(mdates)


def membership_filter(code: np.ndarray, date: np.ndarray,
                      pool_code: np.ndarray,
                      pool_date: np.ndarray) -> np.ndarray:
    """Boolean mask of rows whose ``(code, date)`` is in the membership."""
    if len(pool_code) == 0:
        return np.zeros(len(code), bool)
    key = np.char.add(np.asarray(code, str),
                      np.asarray(date, "datetime64[D]").astype(str))
    pkey = np.unique(np.char.add(np.asarray(pool_code, str),
                                 np.asarray(pool_date,
                                            "datetime64[D]").astype(str)))
    idx = np.searchsorted(pkey, key)
    idx = np.minimum(idx, len(pkey) - 1)
    return pkey[idx] == key


def read_daily_pv(
    path: str,
    columns: Optional[Sequence[str]] = None,
) -> Dict[str, np.ndarray]:
    """Daily price/volume loader with the CSMAR rename table applied.

    ``columns`` selects *canonical* names (post-rename), mirroring the
    reference's projection kwarg (Factor.py:21-31). Dates parse to
    datetime64[D]; ``code`` normalises to zero-padded 6-char strings.
    """
    schema_names = pq.read_schema(path).names
    rename = {k: v for k, v in DAILY_PV_RENAME.items() if k in schema_names}
    inv = {v: k for k, v in rename.items()}
    if columns is None:
        read = schema_names
    else:
        read = [inv.get(c, c) for c in columns]
    raw = read_columns(path, read)
    out = {}
    for k, v in raw.items():
        out[rename.get(k, k)] = v
    if "date" in out:
        out["date"] = coerce_dates(out["date"])
    if "code" in out and out["code"].dtype.kind in "iu":
        out["code"] = int_codes_to_str(out["code"])
    return out
