"""A-share trading sessions and the 240-slot minute grid.

The reference encodes bar timestamps as integers ``HHMMSSmmm`` (hour*1e7), e.g.
``93000000`` = 09:30, ``145900000`` = 14:59, and converts to a "trade minute"
index via minutes-since-midnight (``time // 1e7 * 60 + time % 1e7 / 1e5``) and
a session-offset subtraction (reference
``MinuteFrequentFactorCalculateMethodsCICC.py:98-106``):

    trade_minute = msm - 570   if msm < 720   (morning, 09:30 -> 0)
                 = msm - 660   otherwise      (afternoon, 13:00 -> 120)

Bars are labelled by window *start*: the morning session is 09:30..11:29
(slots 0..119) and the afternoon session 13:00..14:59 (slots 120..239), a
dense 240-slot grid. Note 11:30 would collide with 13:00 at slot 120 under the
reference's formula; canonical data carries no 11:30 bar, and our loader
rejects off-grid timestamps rather than silently aliasing them.

ISSUE 15: this module IS the ``cn_ashare_240`` instance of
:mod:`.markets` — every constant below re-exports that frozen
:class:`~.markets.SessionSpec`'s values byte-for-byte (pinned by
tests/test_markets.py), so the seed's import surface keeps working
while everything session-shaped (``ops/``, ``stream/``, the wire, the
parity harness) parameterizes on a spec. New markets register in
``markets/registry.py``; see docs/sessions.md.
"""

from __future__ import annotations

import numpy as np

from .markets.registry import CN_ASHARE_240 as SPEC

N_SLOTS = SPEC.n_slots
AM_SLOTS = SPEC.segments[0][1]  # 09:30..11:29
PM_SLOTS = SPEC.segments[1][1]  # 13:00..14:59

_AM_OPEN_MSM = SPEC.segments[0][0]   # 570
_PM_OPEN_MSM = SPEC.segments[1][0]   # 780
_NOON_MSM = 720

#: HHMMSSmmm timestamp of every slot (length 240). Kernels express the
#: reference's time filters as boolean masks over this array, e.g.
#: ``GRID_TIMES >= 145700000`` for the last-3-minute window.
GRID_TIMES: np.ndarray = SPEC.grid_times


def time_to_slot(time_int: np.ndarray) -> np.ndarray:
    """Vectorised HHMMSSmmm -> slot index; -1 for off-grid timestamps.

    Off-grid = outside [09:30, 11:30) ∪ [13:00, 15:00), or with a non-zero
    seconds/millis component (the grid is whole minutes).
    """
    return SPEC.time_to_slot(time_int)


def slot_to_time(slot: np.ndarray) -> np.ndarray:
    """Slot index -> HHMMSSmmm (inverse of :func:`time_to_slot`)."""
    return SPEC.slot_to_time(slot)


# Named sentinel times used by the reference kernels
# (MinuteFrequentFactorCalculateMethodsCICC.py:18,33,69,84,770,1212,...).
# Values come from the cn_ashare_240 spec (derived semantically from the
# grid, with T_NOON pinned to the historical 11:30 constant).
T_AM_OPEN = SPEC.T_AM_OPEN
T_AM_CLOSE = SPEC.T_AM_CLOSE
T_NOON = SPEC.T_NOON
T_PM_OPEN = SPEC.T_PM_OPEN
T_PM_CLOSE = SPEC.T_PM_CLOSE
T_LAST30_OPEN = SPEC.T_LAST30_OPEN
T_BETWEEN_OPEN = SPEC.T_BETWEEN_OPEN
T_BETWEEN_CLOSE = SPEC.T_BETWEEN_CLOSE
T_CLOSE_AUCTION = SPEC.T_CLOSE_AUCTION  # last-3-minutes boundary
T_TAIL20 = SPEC.T_TAIL20
T_TAIL50 = SPEC.T_TAIL50
T_HEAD_END = SPEC.T_HEAD_END
T_TOP20_END = SPEC.T_TOP20_END
T_TOP50_END = SPEC.T_TOP50_END
