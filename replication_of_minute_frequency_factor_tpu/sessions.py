"""A-share trading sessions and the 240-slot minute grid.

The reference encodes bar timestamps as integers ``HHMMSSmmm`` (hour*1e7), e.g.
``93000000`` = 09:30, ``145900000`` = 14:59, and converts to a "trade minute"
index via minutes-since-midnight (``time // 1e7 * 60 + time % 1e7 / 1e5``) and
a session-offset subtraction (reference
``MinuteFrequentFactorCalculateMethodsCICC.py:98-106``):

    trade_minute = msm - 570   if msm < 720   (morning, 09:30 -> 0)
                 = msm - 660   otherwise      (afternoon, 13:00 -> 120)

Bars are labelled by window *start*: the morning session is 09:30..11:29
(slots 0..119) and the afternoon session 13:00..14:59 (slots 120..239), a
dense 240-slot grid. Note 11:30 would collide with 13:00 at slot 120 under the
reference's formula; canonical data carries no 11:30 bar, and our loader
rejects off-grid timestamps rather than silently aliasing them.
"""

from __future__ import annotations

import numpy as np

N_SLOTS = 240
AM_SLOTS = 120  # 09:30..11:29
PM_SLOTS = 120  # 13:00..14:59

_AM_OPEN_MSM = 9 * 60 + 30   # 570
_PM_OPEN_MSM = 13 * 60       # 780
_NOON_MSM = 720


def _msm_to_time(msm: np.ndarray) -> np.ndarray:
    """minutes-since-midnight -> HHMMSSmmm integer."""
    return (msm // 60) * 10_000_000 + (msm % 60) * 100_000


def _grid_times() -> np.ndarray:
    slots = np.arange(N_SLOTS)
    msm = np.where(slots < AM_SLOTS, _AM_OPEN_MSM + slots,
                   _PM_OPEN_MSM + (slots - AM_SLOTS))
    return _msm_to_time(msm).astype(np.int64)


#: HHMMSSmmm timestamp of every slot (length 240). Kernels express the
#: reference's time filters as boolean masks over this array, e.g.
#: ``GRID_TIMES >= 145700000`` for the last-3-minute window.
GRID_TIMES: np.ndarray = _grid_times()
GRID_TIMES.setflags(write=False)


def time_to_slot(time_int: np.ndarray) -> np.ndarray:
    """Vectorised HHMMSSmmm -> slot index; -1 for off-grid timestamps.

    Off-grid = outside [09:30, 11:30) ∪ [13:00, 15:00), or with a non-zero
    seconds/millis component (the grid is whole minutes).
    """
    time_int = np.asarray(time_int, dtype=np.int64)
    hm = time_int // 10_000_000 * 60 + (time_int % 10_000_000) // 100_000
    sub_minute = time_int % 100_000 != 0  # seconds/millis present
    am = (hm >= _AM_OPEN_MSM) & (hm < _AM_OPEN_MSM + AM_SLOTS)
    pm = (hm >= _PM_OPEN_MSM) & (hm < _PM_OPEN_MSM + PM_SLOTS)
    slot = np.where(am, hm - (_AM_OPEN_MSM),
                    np.where(pm, hm - _PM_OPEN_MSM + AM_SLOTS, -1))
    slot = np.where(sub_minute, -1, slot)
    return slot.astype(np.int64)


def slot_to_time(slot: np.ndarray) -> np.ndarray:
    """Slot index -> HHMMSSmmm (inverse of :func:`time_to_slot`)."""
    return GRID_TIMES[np.asarray(slot)]


# Named sentinel times used by the reference kernels
# (MinuteFrequentFactorCalculateMethodsCICC.py:18,33,69,84,770,1212,...).
T_AM_OPEN = 93000000
T_AM_CLOSE = 112900000
T_NOON = 113000000
T_PM_OPEN = 130000000
T_PM_CLOSE = 145900000
T_LAST30_OPEN = 143000000
T_BETWEEN_OPEN = 100000000
T_BETWEEN_CLOSE = 142900000
T_CLOSE_AUCTION = 145700000  # last-3-minutes boundary
T_TAIL20 = 144000000
T_TAIL50 = 141000000
T_HEAD_END = 100000000
T_TOP20_END = 95000000
T_TOP50_END = 102000000
