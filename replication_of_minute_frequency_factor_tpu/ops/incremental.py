"""Incremental (per-minute fold) forms of the masked reductions.

The streaming carry (``stream/carry.py``) advances per arriving bar; the
accumulators here are the fold-step twins of the batch reductions in
:mod:`.masked`. They split into two exactness classes, and the split is
the load-bearing design decision of the whole streaming subsystem:

* **Exact under reordering** — integer window counters (associative
  integer adds of 0/1) and pure selections (``first_open``/
  ``last_close`` pick a stored f32 value, no arithmetic). Folding these
  minute-by-minute is *bitwise identical* to the batch reduction over
  the completed mask, so the streaming finalize may inject them into
  :class:`..models.context.DayContext`'s memo and skip the batch
  recompute without perturbing parity.
* **Order-sensitive** — f32 accumulators (``vol_sum`` and the ``st_*``
  sufficient statistics below). A sequential left fold does not
  reproduce XLA's tree reduce bitwise, so these NEVER feed the
  *bitwise* finalize graph: under the default ``finalize_impl='exact'``
  every f32 reduction a kernel consumes is recomputed from the carried
  bar buffer by the batch formulation. That asymmetry is what lets the
  240-increment parity gate (tests/test_stream.py) demand bitwise
  equality. Since ISSUE 18 the same accumulators ARE the fast
  finalize's inputs: ``finalize_impl='fast'`` materializes the
  ``stat_fold`` kernels from these statistics alone
  (``stream/fastpath.py``), trading the bitwise contract for
  per-factor pinned rtol bounds (docs/PIN_BOUNDS.md).

The sufficient statistics (ISSUE 18) extend the carry per lane:

* ``st_ret_*`` / ``st_volu_*`` — streamed Welford first-four central
  moments of per-bar close/open-1 returns and volume (count =
  ``bars``);
* ``st_range_*`` — Welford (mean, M2) of high/low;
* ``st_retpos_*`` / ``st_retneg_*`` — own count + Welford (mean, M2)
  over the signed-return subsets;
* ``st_volsum_<window>`` — windowed f32 volume sums;
* ``st_rv_tail20`` / ``st_rv_tail50`` — windowed sums of ret·volume;
* ``st_amihud`` — the streamed amihud term sum (|pct-close| / volume
  over consecutive present bars);
* ``sel_first_open_<w>`` / ``sel_last_close_<w>`` / ``sel_first_volume``
  — pure selections (reorder-exact, like ``first_open``): the
  sentinel/session-half anchors of the ``exact_fold`` kernels.

Every statistic folds with IDENTICAL per-lane arithmetic on the dense
(:func:`update_inc`) and cohort (:func:`update_inc_at`) paths, so the
PR 7 cohort-vs-scan bitwise carry equality extends to the new leaves.

Window membership mirrors :meth:`..models.context.DayContext.time_mask`
over the HHMMSSmmm grid of :mod:`..sessions` — the counters are the
incremental form of the per-window bar counts every NaN-gating
``jnp.any(sel)`` / ``count(sel)`` in the kernel library reduces to.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax.numpy as jnp

from ..data.minute import F_CLOSE, F_HIGH, F_LOW, F_OPEN, F_VOLUME
from ..markets import get_session

_NAN = jnp.nan

#: windows whose first-open/last-close selections anchor the
#: ``exact_fold`` kernels (sentinel ratios + mmt_paratio's halves)
SEL_WINDOWS = ("am", "pm", "sent_pm", "sent_last30", "sent_am",
               "sent_between")
#: windows whose f32 volume sums feed ``stat_fold`` kernels
VOLSUM_WINDOWS = ("pre_auction", "auction", "head", "tail20", "tail30",
                  "tail50")
#: windows whose ret·volume sums feed the bottom-ret-ratio pair
RV_WINDOWS = ("tail20", "tail50")

#: zero-init f32 statistic leaves (order-sensitive accumulators)
STAT_LEAVES_F32 = (
    "st_ret_mean", "st_ret_m2", "st_ret_m3", "st_ret_m4",
    "st_volu_mean", "st_volu_m2", "st_volu_m3", "st_volu_m4",
    "st_range_mean", "st_range_m2",
    "st_retpos_mean", "st_retpos_m2",
    "st_retneg_mean", "st_retneg_m2",
    "st_amihud",
) + tuple(f"st_volsum_{w}" for w in VOLSUM_WINDOWS) \
  + tuple(f"st_rv_{w}" for w in RV_WINDOWS)
#: zero-init int32 subset counters (reorder-exact)
STAT_LEAVES_I32 = ("st_retpos_n", "st_retneg_n")
#: NaN-init f32 selection leaves (reorder-exact)
SEL_LEAVES = ("sel_first_volume",) + tuple(
    f"sel_{kind}_{w}" for w in SEL_WINDOWS
    for kind in ("first_open", "last_close"))


@functools.lru_cache(maxsize=None)
def window_counters_for(session=None) -> Dict[str, Tuple]:
    """Counter name -> window spec for one market session (ISSUE 15).

    ``("range", lo, hi, lo_strict, hi_strict)`` bounds the slot time
    like ``DayContext.time_mask`` (None = unbounded); ``("exact",
    times)`` matches the sentinel-bar kernels' 2-slot candidate sets.
    The per-kernel readiness requirements
    (``models.registry.STREAM_REQUIREMENTS``) name these counters —
    the NAMES are session-relative (every spec defines the same
    windows at its own boundaries), so one readiness contract serves
    every registered market. Cached per spec: specs are frozen, and
    the dict is consulted at trace time."""
    s = get_session(session)
    return {
        "bars": ("range", None, None, False, False),
        "am": ("range", None, s.T_NOON, False, False),
        "pm": ("range", s.T_NOON, None, True, False),
        "pre_auction": ("range", None, s.T_CLOSE_AUCTION, False, True),
        "auction": ("range", s.T_CLOSE_AUCTION, None, False, False),
        "head": ("range", None, s.T_HEAD_END, False, False),
        "top20": ("range", None, s.T_TOP20_END, False, False),
        "top50": ("range", None, s.T_TOP50_END, False, False),
        "tail20": ("range", s.T_TAIL20, None, False, False),
        "tail30": ("range", s.T_LAST30_OPEN, None, False, False),
        "tail50": ("range", s.T_TAIL50, None, False, False),
        "sent_pm": ("exact", (s.T_PM_OPEN, s.T_PM_CLOSE)),
        "sent_last30": ("exact", (s.T_LAST30_OPEN, s.T_PM_CLOSE)),
        "sent_am": ("exact", (s.T_AM_OPEN, s.T_AM_CLOSE)),
        "sent_between": ("exact", (s.T_BETWEEN_OPEN, s.T_BETWEEN_CLOSE)),
    }


#: the canonical cn_ashare_240 windows (the seed's module constant;
#: counter NAMES — what the readiness contract validates against — are
#: identical for every session)
WINDOW_COUNTERS: Dict[str, Tuple] = window_counters_for(None)


def window_contains(spec: Tuple, time):
    """Traced bool: does the (scalar) HHMMSSmmm ``time`` fall inside
    the static window ``spec``? The spec is static, so the comparison
    chain is built at trace time — no masks materialize."""
    kind = spec[0]
    if kind == "exact":
        hit = False
        for t in spec[1]:
            hit = hit | (time == t)
        return hit
    _, lo, hi, lo_strict, hi_strict = spec
    ok = True
    if lo is not None:
        ok = ok & ((time > lo) if lo_strict else (time >= lo))
    if hi is not None:
        ok = ok & ((time < hi) if hi_strict else (time <= hi))
    return ok


def init_inc(n_tickers: int) -> Dict[str, object]:
    """Zero-state accumulators for ``n_tickers`` lanes (host numpy —
    the engine device_puts the whole carry explicitly once)."""
    import numpy as np

    out: Dict[str, object] = {
        name: np.zeros((n_tickers,), np.int32) for name in WINDOW_COUNTERS}
    out["vol_sum"] = np.zeros((n_tickers,), np.float32)
    out["first_open"] = np.full((n_tickers,), np.nan, np.float32)
    out["last_close"] = np.full((n_tickers,), np.nan, np.float32)
    for name in STAT_LEAVES_F32:
        out[name] = np.zeros((n_tickers,), np.float32)
    for name in STAT_LEAVES_I32:
        out[name] = np.zeros((n_tickers,), np.int32)
    for name in SEL_LEAVES:
        out[name] = np.full((n_tickers,), np.nan, np.float32)
    return out


def _welford_step(n_old_f, mean, m2, x):
    """Per-lane Welford fold of (mean, M2) for one observation ``x``.

    ``n_old_f`` is the PRE-update observation count as f32. The same
    function body serves the dense and cohort ingest paths — identical
    per-lane arithmetic is what extends the PR 7 cohort<->scan bitwise
    carry equality to the statistic leaves. Every increment to M2 is
    ``delta * (delta/n) * n_old`` — a same-sign product, so M2 stays
    non-negative in f32 too.
    """
    n = n_old_f + 1.0
    delta = x - mean
    delta_n = delta / n
    return mean + delta_n, m2 + delta * delta_n * n_old_f


def _welford4_step(n_old_f, mean, m2, m3, m4, x):
    """Per-lane fold of the first four central moments (Pébay's
    one-observation update). The M2 line is the :func:`_welford_step`
    arithmetic verbatim."""
    n = n_old_f + 1.0
    delta = x - mean
    delta_n = delta / n
    delta_n2 = delta_n * delta_n
    term1 = delta * delta_n * n_old_f
    new_m4 = m4 + (term1 * delta_n2 * (n * n - 3.0 * n + 3.0)
                   + 6.0 * delta_n2 * m2 - 4.0 * delta_n * m3)
    new_m3 = m3 + term1 * delta_n * (n - 2.0) - 3.0 * delta_n * m2
    return mean + delta_n, m2 + term1, new_m3, new_m4


def _fold_stats(get, open_, high, low, close, volume, present, inw):
    """Post-bar values of every sufficient-statistic leaf (ISSUE 18).

    ``get(name)`` returns the PRE-update per-lane value of a carry leaf:
    the dense path passes ``inc.__getitem__`` (full ``[T]`` arrays), the
    cohort path a clip-mode gather at the cohort's indices (``[K]``
    rows). ``inw[window]`` is the trace-time scalar bool of slot
    membership; ``present`` gates lanes (the cohort passes ``True`` —
    its rows are present by construction). Both ingest paths route
    through THIS function, so the per-lane arithmetic is shared by
    construction. Per-bar inputs reuse the batch formulations exactly
    (``ret = (close-open)/open`` as ``DayContext.ret_co``, amihud's
    ``(close-prev)/prev`` as ``pct_change_valid``), so each bar's
    contribution is the bitwise-same f32 value the batch kernel sees —
    only the accumulation order differs (the pinned-bound residual).
    """
    out = {}
    bars_old = get("bars")
    nf = bars_old.astype(jnp.float32)
    ret = (close - open_) / open_
    rng = high / low

    # first-four-moment Welford series over all present bars
    for leaf, x in (("ret", ret), ("volu", volume)):
        ks = tuple(f"st_{leaf}_{p}" for p in ("mean", "m2", "m3", "m4"))
        new = _welford4_step(nf, *(get(k) for k in ks), x)
        for k, v in zip(ks, new):
            out[k] = jnp.where(present, v, get(k))
    n_mean, n_m2 = _welford_step(nf, get("st_range_mean"),
                                 get("st_range_m2"), rng)
    out["st_range_mean"] = jnp.where(present, n_mean, get("st_range_mean"))
    out["st_range_m2"] = jnp.where(present, n_m2, get("st_range_m2"))

    # signed-return subsets carry their own counts
    for leaf, cond in (("retpos", ret > 0), ("retneg", ret < 0)):
        sel = present & cond
        n_old = get(f"st_{leaf}_n")
        mean, m2 = get(f"st_{leaf}_mean"), get(f"st_{leaf}_m2")
        n_mean, n_m2 = _welford_step(n_old.astype(jnp.float32), mean, m2,
                                     ret)
        out[f"st_{leaf}_n"] = n_old + jnp.where(sel, jnp.int32(1),
                                                jnp.int32(0))
        out[f"st_{leaf}_mean"] = jnp.where(sel, n_mean, mean)
        out[f"st_{leaf}_m2"] = jnp.where(sel, n_m2, m2)

    # windowed f32 sums
    for w in VOLSUM_WINDOWS:
        sel = present & inw[w]
        out[f"st_volsum_{w}"] = get(f"st_volsum_{w}") + jnp.where(
            sel, volume, 0.0)
    for w in RV_WINDOWS:
        sel = present & inw[w]
        out[f"st_rv_{w}"] = get(f"st_rv_{w}") + jnp.where(
            sel, ret * volume, 0.0)

    # amihud term sum: |pct change over consecutive present closes| /
    # volume; the first present bar contributes 0 exactly as the batch
    # kernel's null-filled first pct (0/volume == 0.0 when volume > 0)
    prev = get("last_close")
    has_prev = bars_old > 0
    pct_abs = jnp.where(has_prev, jnp.abs((close - prev) / prev), 0.0)
    term = jnp.where(volume > 0.0, pct_abs / volume, 0.0)
    out["st_amihud"] = get("st_amihud") + jnp.where(present, term, 0.0)

    # pure selections (reorder-exact anchors of the exact_fold kernels);
    # in-order ingestion makes first-arrival == first-slot
    never = bars_old == 0
    out["sel_first_volume"] = jnp.where(never & present, volume,
                                        get("sel_first_volume"))
    for w in SEL_WINDOWS:
        sel = present & inw[w]
        unseen = get(w) == 0
        out[f"sel_first_open_{w}"] = jnp.where(
            sel & unseen, open_, get(f"sel_first_open_{w}"))
        out[f"sel_last_close_{w}"] = jnp.where(
            sel, close, get(f"sel_last_close_{w}"))
    return out


def _stat_windows(wc):
    """The window specs the statistic fold consults."""
    need = set(SEL_WINDOWS) | set(VOLSUM_WINDOWS) | set(RV_WINDOWS)
    return {w: wc[w] for w in need}


def update_inc(inc, t, values, present, session=None):
    """One-minute fold step: bump every window counter for the present
    lanes and advance the selection trackers.

    ``t`` is the (traced) slot index of this minute, ``values [T, 5]``
    the bar fields, ``present [T]`` which tickers traded this minute.
    Integer counters and first/last selections stay bitwise-equal to
    their batch forms (module docstring); ``vol_sum`` is the
    order-sensitive diagnostic accumulator. ``session`` picks the
    window boundaries (trace-time static; None = cn_ashare_240).
    """
    sess = get_session(session)
    wc = window_counters_for(sess)
    time = jnp.asarray(sess.grid_times)[t]
    out = dict(inc)
    one = jnp.int32(1)
    for name, spec in wc.items():
        out[name] = inc[name] + jnp.where(
            present & window_contains(spec, time), one, jnp.int32(0))
    out["vol_sum"] = inc["vol_sum"] + jnp.where(
        present, values[..., F_VOLUME], 0.0)
    out["last_close"] = jnp.where(present, values[..., F_CLOSE],
                                  inc["last_close"])
    never_seen = inc["bars"] == 0
    out["first_open"] = jnp.where(never_seen & present,
                                  values[..., F_OPEN], inc["first_open"])
    inw = {w: window_contains(spec, time)
           for w, spec in _stat_windows(wc).items()}
    out.update(_fold_stats(
        inc.__getitem__, values[..., F_OPEN], values[..., F_HIGH],
        values[..., F_LOW], values[..., F_CLOSE], values[..., F_VOLUME],
        present, inw))
    return out


def update_inc_at(inc, t, rows, idx, session=None):
    """Cohort (scatter) twin of :func:`update_inc`: ``rows [K, 5]`` are
    bars for tickers ``idx [K]`` at slot ``t``. Padding rows carry an
    out-of-bounds index (``idx == n_tickers``) and are dropped by the
    scatter. Each ticker appears at most once per call (live feeds
    deliver one bar per ticker per minute); duplicates are undefined.
    """
    sess = get_session(session)
    wc = window_counters_for(sess)
    time = jnp.asarray(sess.grid_times)[t]
    out = dict(inc)
    for name, spec in wc.items():
        bump = jnp.where(window_contains(spec, time), jnp.int32(1),
                         jnp.int32(0))
        bump = jnp.broadcast_to(bump, idx.shape)
        out[name] = inc[name].at[idx].add(bump, mode="drop")
    out["vol_sum"] = inc["vol_sum"].at[idx].add(rows[..., F_VOLUME],
                                                mode="drop")
    out["last_close"] = inc["last_close"].at[idx].set(rows[..., F_CLOSE],
                                                      mode="drop")
    # gather-then-scatter: padding lanes gather clamped garbage, but
    # the drop-mode scatter never writes it back
    seen = inc["bars"].at[idx].get(mode="clip") > 0
    first = jnp.where(seen, inc["first_open"].at[idx].get(mode="clip"),
                      rows[..., F_OPEN])
    out["first_open"] = inc["first_open"].at[idx].set(first, mode="drop")
    # statistic leaves: gather the cohort's pre-update rows, run the
    # SAME per-lane fold as the dense path, scatter-set the results
    # (non-selected rows write their old value back — a value no-op)
    inw = {w: window_contains(spec, time)
           for w, spec in _stat_windows(wc).items()}
    new_rows = _fold_stats(
        lambda k: inc[k].at[idx].get(mode="clip"),
        rows[..., F_OPEN], rows[..., F_HIGH], rows[..., F_LOW],
        rows[..., F_CLOSE], rows[..., F_VOLUME], True, inw)
    for k, v in new_rows.items():
        out[k] = inc[k].at[idx].set(v, mode="drop")
    return out
