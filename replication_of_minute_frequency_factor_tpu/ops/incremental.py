"""Incremental (per-minute fold) forms of the masked reductions.

The streaming carry (``stream/carry.py``) advances per arriving bar; the
accumulators here are the fold-step twins of the batch reductions in
:mod:`.masked`. They split into two exactness classes, and the split is
the load-bearing design decision of the whole streaming subsystem:

* **Exact under reordering** — integer window counters (associative
  integer adds of 0/1) and pure selections (``first_open``/
  ``last_close`` pick a stored f32 value, no arithmetic). Folding these
  minute-by-minute is *bitwise identical* to the batch reduction over
  the completed mask, so the streaming finalize may inject them into
  :class:`..models.context.DayContext`'s memo and skip the batch
  recompute without perturbing parity.
* **Order-sensitive** — f32 accumulators (``vol_sum`` here). A
  sequential left fold does not reproduce XLA's tree reduce bitwise,
  so these NEVER feed the finalize graph: they exist for telemetry and
  readiness only, and every f32 reduction a kernel consumes is
  recomputed from the carried bar buffer by the batch formulation.
  That asymmetry is what lets the 240-increment parity gate
  (tests/test_stream.py) demand bitwise equality.

Window membership mirrors :meth:`..models.context.DayContext.time_mask`
over the HHMMSSmmm grid of :mod:`..sessions` — the counters are the
incremental form of the per-window bar counts every NaN-gating
``jnp.any(sel)`` / ``count(sel)`` in the kernel library reduces to.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax.numpy as jnp

from ..data.minute import F_CLOSE, F_OPEN, F_VOLUME
from ..markets import get_session

_NAN = jnp.nan


@functools.lru_cache(maxsize=None)
def window_counters_for(session=None) -> Dict[str, Tuple]:
    """Counter name -> window spec for one market session (ISSUE 15).

    ``("range", lo, hi, lo_strict, hi_strict)`` bounds the slot time
    like ``DayContext.time_mask`` (None = unbounded); ``("exact",
    times)`` matches the sentinel-bar kernels' 2-slot candidate sets.
    The per-kernel readiness requirements
    (``models.registry.STREAM_REQUIREMENTS``) name these counters —
    the NAMES are session-relative (every spec defines the same
    windows at its own boundaries), so one readiness contract serves
    every registered market. Cached per spec: specs are frozen, and
    the dict is consulted at trace time."""
    s = get_session(session)
    return {
        "bars": ("range", None, None, False, False),
        "am": ("range", None, s.T_NOON, False, False),
        "pm": ("range", s.T_NOON, None, True, False),
        "pre_auction": ("range", None, s.T_CLOSE_AUCTION, False, True),
        "auction": ("range", s.T_CLOSE_AUCTION, None, False, False),
        "head": ("range", None, s.T_HEAD_END, False, False),
        "top20": ("range", None, s.T_TOP20_END, False, False),
        "top50": ("range", None, s.T_TOP50_END, False, False),
        "tail20": ("range", s.T_TAIL20, None, False, False),
        "tail30": ("range", s.T_LAST30_OPEN, None, False, False),
        "tail50": ("range", s.T_TAIL50, None, False, False),
        "sent_pm": ("exact", (s.T_PM_OPEN, s.T_PM_CLOSE)),
        "sent_last30": ("exact", (s.T_LAST30_OPEN, s.T_PM_CLOSE)),
        "sent_am": ("exact", (s.T_AM_OPEN, s.T_AM_CLOSE)),
        "sent_between": ("exact", (s.T_BETWEEN_OPEN, s.T_BETWEEN_CLOSE)),
    }


#: the canonical cn_ashare_240 windows (the seed's module constant;
#: counter NAMES — what the readiness contract validates against — are
#: identical for every session)
WINDOW_COUNTERS: Dict[str, Tuple] = window_counters_for(None)


def window_contains(spec: Tuple, time):
    """Traced bool: does the (scalar) HHMMSSmmm ``time`` fall inside
    the static window ``spec``? The spec is static, so the comparison
    chain is built at trace time — no masks materialize."""
    kind = spec[0]
    if kind == "exact":
        hit = False
        for t in spec[1]:
            hit = hit | (time == t)
        return hit
    _, lo, hi, lo_strict, hi_strict = spec
    ok = True
    if lo is not None:
        ok = ok & ((time > lo) if lo_strict else (time >= lo))
    if hi is not None:
        ok = ok & ((time < hi) if hi_strict else (time <= hi))
    return ok


def init_inc(n_tickers: int) -> Dict[str, object]:
    """Zero-state accumulators for ``n_tickers`` lanes (host numpy —
    the engine device_puts the whole carry explicitly once)."""
    import numpy as np

    out: Dict[str, object] = {
        name: np.zeros((n_tickers,), np.int32) for name in WINDOW_COUNTERS}
    out["vol_sum"] = np.zeros((n_tickers,), np.float32)
    out["first_open"] = np.full((n_tickers,), np.nan, np.float32)
    out["last_close"] = np.full((n_tickers,), np.nan, np.float32)
    return out


def update_inc(inc, t, values, present, session=None):
    """One-minute fold step: bump every window counter for the present
    lanes and advance the selection trackers.

    ``t`` is the (traced) slot index of this minute, ``values [T, 5]``
    the bar fields, ``present [T]`` which tickers traded this minute.
    Integer counters and first/last selections stay bitwise-equal to
    their batch forms (module docstring); ``vol_sum`` is the
    order-sensitive diagnostic accumulator. ``session`` picks the
    window boundaries (trace-time static; None = cn_ashare_240).
    """
    sess = get_session(session)
    time = jnp.asarray(sess.grid_times)[t]
    out = dict(inc)
    one = jnp.int32(1)
    for name, spec in window_counters_for(sess).items():
        out[name] = inc[name] + jnp.where(
            present & window_contains(spec, time), one, jnp.int32(0))
    out["vol_sum"] = inc["vol_sum"] + jnp.where(
        present, values[..., F_VOLUME], 0.0)
    out["last_close"] = jnp.where(present, values[..., F_CLOSE],
                                  inc["last_close"])
    never_seen = inc["bars"] == 0
    out["first_open"] = jnp.where(never_seen & present,
                                  values[..., F_OPEN], inc["first_open"])
    return out


def update_inc_at(inc, t, rows, idx, session=None):
    """Cohort (scatter) twin of :func:`update_inc`: ``rows [K, 5]`` are
    bars for tickers ``idx [K]`` at slot ``t``. Padding rows carry an
    out-of-bounds index (``idx == n_tickers``) and are dropped by the
    scatter. Each ticker appears at most once per call (live feeds
    deliver one bar per ticker per minute); duplicates are undefined.
    """
    sess = get_session(session)
    time = jnp.asarray(sess.grid_times)[t]
    out = dict(inc)
    for name, spec in window_counters_for(sess).items():
        bump = jnp.where(window_contains(spec, time), jnp.int32(1),
                         jnp.int32(0))
        bump = jnp.broadcast_to(bump, idx.shape)
        out[name] = inc[name].at[idx].add(bump, mode="drop")
    out["vol_sum"] = inc["vol_sum"].at[idx].add(rows[..., F_VOLUME],
                                                mode="drop")
    out["last_close"] = inc["last_close"].at[idx].set(rows[..., F_CLOSE],
                                                      mode="drop")
    # gather-then-scatter: padding lanes gather clamped garbage, but
    # the drop-mode scatter never writes it back
    seen = inc["bars"].at[idx].get(mode="clip") > 0
    first = jnp.where(seen, inc["first_open"].at[idx].get(mode="clip"),
                      rows[..., F_OPEN])
    out["first_open"] = inc["first_open"].at[idx].set(first, mode="drop")
    return out
