"""Masked reductions along the last axis, matching polars defaults.

Conventions (SURVEY.md §2.5 Q11):
  * null == masked-out lane: skipped by sum/mean/std/skew/kurtosis/corr;
  * NaN inside a valid lane propagates (polars treats NaN as a float value);
  * ``std``/``var`` default ``ddof=1``; result is null (NaN here) when
    ``n <= ddof``;
  * ``skew`` is the biased Fisher-Pearson g1 = m3 / m2^1.5;
  * ``kurtosis`` is biased Fisher excess = m4 / m2^2 - 3;
  * ``corr`` is Pearson over pairwise-valid lanes.

All functions broadcast over leading dims and reduce the trailing axis, so the
same code serves ``[240]``, ``[T, 240]`` and ``[D, T, 240]`` tensors — the
XLA-friendly formulation of the reference's ``group_by(['code','date'])``
aggregations. Central moments use the two-pass (subtract-mean) form for f32
stability on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NAN = jnp.nan


def cummax_last(a):
    """Running max along the last axis (``jnp.maximum.accumulate``
    semantics; that ufunc method does not exist on this jax, and
    ``lax.cummax`` rejects negative axes)."""
    return jax.lax.cummax(a, axis=a.ndim - 1)


def count(mask):
    return jnp.sum(mask, axis=-1)


def masked_sum(x, mask):
    return jnp.sum(jnp.where(mask, x, 0.0), axis=-1)


def masked_mean(x, mask):
    n = count(mask)
    s = masked_sum(x, mask)
    return jnp.where(n > 0, s / jnp.maximum(n, 1), _NAN)


def _central_moment(x, mask, mu, k):
    d = jnp.where(mask, x - mu[..., None], 0.0)
    return jnp.sum(d**k, axis=-1)


def masked_var(x, mask, ddof: int = 1):
    n = count(mask)
    mu = masked_mean(x, mask)
    m2 = _central_moment(x, mask, mu, 2)
    denom = jnp.maximum(n - ddof, 1)
    return jnp.where(n > ddof, m2 / denom, _NAN)


def masked_std(x, mask, ddof: int = 1):
    return jnp.sqrt(masked_var(x, mask, ddof=ddof))


def masked_skew(x, mask):
    """Biased Fisher-Pearson g1 (polars ``Expr.skew(bias=True)`` default)."""
    n = count(mask)
    mu = masked_mean(x, mask)
    nn = jnp.maximum(n, 1)
    m2 = _central_moment(x, mask, mu, 2) / nn
    m3 = _central_moment(x, mask, mu, 3) / nn
    g1 = m3 / jnp.power(m2, 1.5)  # m2 == 0 -> NaN/inf, as polars
    return jnp.where(n > 0, g1, _NAN)


def masked_kurtosis(x, mask):
    """Biased Fisher excess kurtosis (polars ``Expr.kurtosis()`` default)."""
    n = count(mask)
    mu = masked_mean(x, mask)
    nn = jnp.maximum(n, 1)
    m2 = _central_moment(x, mask, mu, 2) / nn
    m4 = _central_moment(x, mask, mu, 4) / nn
    g2 = m4 / (m2 * m2) - 3.0
    return jnp.where(n > 0, g2, _NAN)


def masked_corr(x, y, mask):
    """Pearson correlation over pairwise-valid lanes (polars ``pl.corr``).

    Both series are anchored to their first valid value before the moment
    pass: correlation is shift-invariant, and the anchoring makes a
    constant series yield *exactly* zero variance in f32 (hence NaN, as the
    f64 oracle) instead of rounding noise posing as signal. (An all-invalid
    row anchors to NaN, but the final ``n > 1`` gate forces NaN there
    anyway.)

    The anchor is the production side of the ``constant_window`` pin
    (pins.py): under the alternative ``"noise"`` reading it is skipped at
    trace time, letting raw accumulation noise decide degenerate lanes —
    inherently substrate-dependent, exactly like real polars' two-pass
    variance (``pins.pinned`` clears jit caches so the flip retraces).
    """
    from replication_of_minute_frequency_factor_tpu import pins

    n = count(mask)
    if pins.reading("constant_window") == "degenerate":
        x = x - masked_first(x, mask)[..., None]
        y = y - masked_first(y, mask)[..., None]
    mx = masked_mean(x, mask)
    my = masked_mean(y, mask)
    dx = jnp.where(mask, x - mx[..., None], 0.0)
    dy = jnp.where(mask, y - my[..., None], 0.0)
    cov = jnp.sum(dx * dy, axis=-1)
    vx = jnp.sum(dx * dx, axis=-1)
    vy = jnp.sum(dy * dy, axis=-1)
    r = cov / jnp.sqrt(vx * vy)  # zero variance -> NaN, as polars
    return jnp.where(n > 1, r, _NAN)


def masked_product(x, mask):
    return jnp.prod(jnp.where(mask, x, 1.0), axis=-1)


def masked_min(x, mask):
    n = count(mask)
    m = jnp.min(jnp.where(mask, x, jnp.inf), axis=-1)
    return jnp.where(n > 0, m, _NAN)


def masked_max(x, mask):
    n = count(mask)
    m = jnp.max(jnp.where(mask, x, -jnp.inf), axis=-1)
    return jnp.where(n > 0, m, _NAN)


def _first_valid_index(mask):
    return jnp.argmax(mask, axis=-1)


def _last_valid_index(mask):
    L = mask.shape[-1]
    return L - 1 - jnp.argmax(mask[..., ::-1], axis=-1)


def masked_first(x, mask):
    """Value at the first valid lane (polars ``.first()`` on the group)."""
    idx = _first_valid_index(mask)
    v = jnp.take_along_axis(x, idx[..., None], axis=-1)[..., 0]
    return jnp.where(count(mask) > 0, v, _NAN)


def masked_last(x, mask):
    idx = _last_valid_index(mask)
    v = jnp.take_along_axis(x, idx[..., None], axis=-1)[..., 0]
    return jnp.where(count(mask) > 0, v, _NAN)


def ffill(x, mask):
    """Forward-fill values over invalid lanes (last valid value so far).

    Lanes before the first valid lane are left as NaN. Returns
    ``(filled, has_prev)`` where ``has_prev[..., i]`` says lane i has seen at
    least one valid lane at or before i.
    """
    L = x.shape[-1]
    idx = jnp.arange(L)
    last_valid = cummax_last(jnp.where(mask, idx, -1))
    has_prev = last_valid >= 0
    filled = jnp.take_along_axis(x, jnp.maximum(last_valid, 0), axis=-1)
    return jnp.where(has_prev, filled, _NAN), has_prev


def shift_valid(x, mask, periods: int = 1):
    """Shift over the *valid* lanes only — the dense-grid analogue of polars
    ``shift(periods)`` on a group whose rows are the present bars in slot
    order. Returns ``(values, out_mask)``: for ``periods=1`` each valid lane
    receives the previous valid lane's value (null at the first valid lane).

    Only |periods| == 1 is needed by the reference kernels
    (``corr_pvd``/``corr_pvl``, MinuteFrequentFactorCalculateMethodsCICC.py:899,913).
    """
    if periods == 0:
        return x, mask
    L = x.shape[-1]
    idx = jnp.arange(L)
    if periods > 0:
        if periods != 1:
            raise NotImplementedError("only |periods| <= 1 supported")
        last_valid = cummax_last(jnp.where(mask, idx, -1))
        # previous valid index *strictly before* lane i
        prev = jnp.concatenate(
            [jnp.full(last_valid.shape[:-1] + (1,), -1, last_valid.dtype),
             last_valid[..., :-1]], axis=-1)
        ok = mask & (prev >= 0)
        vals = jnp.take_along_axis(x, jnp.maximum(prev, 0), axis=-1)
        return jnp.where(ok, vals, _NAN), ok
    else:
        if periods != -1:
            raise NotImplementedError("only |periods| <= 1 supported")
        rx, rm = shift_valid(x[..., ::-1], mask[..., ::-1], 1)
        return rx[..., ::-1], rm[..., ::-1]


def pct_change_valid(x, mask):
    """Percent change over consecutive *valid* lanes (polars
    ``pct_change()`` within a group of present bars). Null at the first
    valid lane. Returns ``(values, out_mask)``.

    Uses (x - prev)/prev for f32 accuracy (see ``DayContext.ret_co``)."""
    prev, ok = shift_valid(x, mask, 1)
    vals = (x - prev) / prev
    return jnp.where(ok, vals, _NAN), ok
