"""Rolling-window regression statistics over the 240-slot minute grid.

The ``mmt_ols_*`` family (reference
MinuteFrequentFactorCalculateMethodsCICC.py:93-376) runs polars
``.rolling(index_column='minute_in_trade', period='50i')``: the window at
trade-minute m covers *index values* (m-50, m] — i.e. slots [m-49, m] on our
dense grid — and windows with fewer than 50 present bars are dropped
(``.filter(pl.len() >= 50)``, :129). Because the interval spans exactly 50
integer slots, a window is kept iff every slot in it holds a bar, which makes
the dense formulation exact: compute stats at every slot via cumulative sums
and mark a window valid when its masked count equals ``window``.

Numerical note: cov/var are shift-invariant, so second moments run on
*day-mean-centred* prices (raw CNY-price squares would eat the f32
mantissa), and windowed sums are a ones-kernel convolution rather than a
difference of cumulative sums: each window is then an independent 50-term
dot product on the MXU, avoiding the prefix-sum cancellation that costs
~3 digits at f32 (observed 5e-3 relative error in ``mmt_ols_qrs`` vs the
f64 oracle with the cumsum formulation; ~1e-6 with the conv one). Raw
windowed means (needed for the reference's beta fallback ``mean_y/mean_x``,
:130-134) use the same path. Second moments accumulate squared deviations
over the window offsets directly — Σ_j (x[m-j] - μ_w[m])² — so no
near-equal subtraction ever happens; the E[x²]-μ² shortcut is forbidden.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from .masked import masked_mean

#: rolling backends: 'conv' (fused XLA formulation), 'pallas' (VMEM-resident
#: Pallas TPU kernel for the second-moment pass, auto-falls back to 'conv'
#: off-TPU or when Pallas is unavailable), 'pallas_interpret' (the same
#: kernel on the Pallas interpreter — CPU-safe, for parity tests)
ROLLING_IMPLS = ("conv", "pallas", "pallas_interpret")


def _windowed_sum(a, window: int):
    """Inclusive trailing-window sums: out[..., m] = sum(a[..., m-W+1 : m+1])."""
    a = jnp.asarray(a)  # canonicalizes dtype (f64 -> f32 when x64 is off)
    lead, L = a.shape[:-1], a.shape[-1]
    dt = a.dtype if jnp.issubdtype(a.dtype, jnp.floating) else jnp.float32
    x = a.astype(dt).reshape((-1, 1, L))
    k = jnp.ones((1, 1, window), dt)
    out = jax.lax.conv_general_dilated(
        x, k, window_strides=(1,), padding=[(window - 1, 0)],
        dimension_numbers=("NCH", "OIH", "NCH"),
        precision=jax.lax.Precision.HIGHEST)
    return out.reshape(lead + (L,))


#: window offsets materialized per gather in the fused second-moment
#: pass: bounds the live patch tensor to ``[..., L, MOMENT_CHUNK]``
#: (~0.5 GB/chunk-pair at the 8-day x 5000-ticker shape instead of
#: ~1.9 GB for the full 50-offset window) while staying fully unrolled —
#: no ``while`` op, no serial dependency between offsets. 25 measured
#: fastest of {5, 10, 25, 50} on XLA-CPU (501 ms vs 600/605/891 on the
#: [8, 1000, 240] probe) and halves the peak patch footprint vs 50.
MOMENT_CHUNK = 25


def _window_chunk(a, lo: int, hi: int):
    """Trailing-window offsets ``[lo, hi)`` materialized as one strided
    gather: ``out[..., m, k] = a[..., m - (lo + k)]``, zero-filled where
    the index runs off the left edge (those lanes only reach windows
    whose masked count is already short — invalid slots by construction).
    """
    a = jnp.asarray(a)
    L = a.shape[-1]
    pad = [(0, 0)] * (a.ndim - 1) + [(hi - 1, 0)]
    ap = jnp.pad(a, pad)
    idx = ((hi - 1 + jnp.arange(L)[:, None])
           - (lo + jnp.arange(hi - lo))[None, :])
    return ap[..., idx]


def _second_moments_conv(xc, yc, mu_x, mu_y, window: int,
                         chunk: int = MOMENT_CHUNK):
    """Σ_j d_j², Σ_j e_j², Σ_j d_j·e_j with d_j = x[m-j] - μ_w[m]: the
    trailing windows are materialized by strided gather (``chunk``
    offsets at a time, statically unrolled) and each chunk collapses
    through three batched window dot products in one fused
    multiply-reduce over the offset axis. Replaces the former sequential
    ``fori_loop``-of-``jnp.roll`` accumulation — 50 *dependent*
    full-tensor passes whose loop-carried carry serialized the graph —
    with ⌈W/chunk⌉ independent gather+reduce fusions and no ``while`` op
    in the module (pinned by tests/test_rolling_engine.py's HLO check).

    Windows touching the zero-filled left edge produce garbage — only at
    slots whose window is incomplete, i.e. already invalid.
    """
    s_xx = s_yy = s_xy = None
    for c0 in range(0, window, chunk):
        c1 = min(c0 + chunk, window)
        wx = _window_chunk(xc, c0, c1) - mu_x[..., None]
        wy = _window_chunk(yc, c0, c1) - mu_y[..., None]
        t_xx = jnp.sum(wx * wx, axis=-1)
        t_yy = jnp.sum(wy * wy, axis=-1)
        t_xy = jnp.sum(wx * wy, axis=-1)
        if s_xx is None:
            s_xx, s_yy, s_xy = t_xx, t_yy, t_xy
        else:
            s_xx, s_yy, s_xy = s_xx + t_xx, s_yy + t_yy, s_xy + t_xy
    return s_xx, s_yy, s_xy


def _resolve_impl(impl: str) -> str:
    """Resolve the requested backend to the one that will actually trace.

    ``'pallas'`` needs a real TPU backend AND an importable Pallas; any
    other platform falls back to the fused conv path (the kernel exists
    for VMEM residency, which only means something on the hardware).
    Resolution happens at trace time; the outcome is counted in the run
    registry (``rolling.impl{requested=,resolved=}``) so attribution
    output says which backend actually ran.
    """
    if impl not in ROLLING_IMPLS:
        raise ValueError(f"unknown rolling_impl {impl!r}; "
                         f"expected one of {ROLLING_IMPLS}")
    resolved = impl
    if impl == "pallas":
        try:
            on_tpu = jax.default_backend() == "tpu"
        except Exception:  # noqa: BLE001 — backend init can fail late
            on_tpu = False
        if not on_tpu:
            resolved = "conv"
        else:
            from . import rolling_pallas
            if not rolling_pallas.available():
                resolved = "conv"
    try:  # trace-time only (once per compile), never per-step cost
        from ..telemetry import get_telemetry
        get_telemetry().counter("rolling.impl", requested=impl,
                                resolved=resolved)
    except Exception:  # noqa: BLE001 — telemetry must never break compute
        pass
    return resolved


def rolling_window_stats(x, y, mask, window: int = 50,
                         impl: str = None) -> Dict[str, jnp.ndarray]:
    """Per-slot trailing-window moments of (x, y) over valid bars.

    Returns dict of ``[..., L]`` arrays:
      ``valid``   — window complete (all ``window`` slots hold bars)
      ``mean_x``/``mean_y`` — raw windowed means
      ``cov``     — windowed covariance, ddof=0
      ``var_x``/``var_y`` — windowed variances, ddof=0

    Stats are only meaningful where ``valid``; other lanes are garbage and
    must be masked by the caller.

    ``impl`` (see :data:`ROLLING_IMPLS`): ``'conv'`` — the fused XLA
    formulation (trailing windows gathered once, second moments as one
    batched Gram dot); ``'pallas'`` — a VMEM-resident Pallas TPU kernel
    for the second-moment pass (:mod:`.rolling_pallas`), automatically
    falling back to ``'conv'`` off-TPU; ``'pallas_interpret'`` — the
    same kernel on the Pallas interpreter (CPU-safe, parity tests).
    None reads ``Config.rolling_impl``. Counts/means/validity always
    come from the shared conv path, so they are bit-identical across
    backends — only the second moments (cov/var) are backend-computed.
    The parameter is threaded through registry/pipeline/collectives so
    the choice is always part of every jit cache key.
    """
    from replication_of_minute_frequency_factor_tpu import pins

    if impl is None:
        from ..config import get_config
        impl = get_config().rolling_impl
    impl = _resolve_impl(impl)
    degenerate = pins.reading("constant_window") == "degenerate"
    m = mask.astype(x.dtype)
    xm = jnp.where(mask, x, 0.0)
    ym = jnp.where(mask, y, 0.0)

    n_w = _windowed_sum(m, window)
    valid = n_w > window - 0.5  # robust count equality for float window sums

    sum_x = _windowed_sum(xm, window)
    sum_y = _windowed_sum(ym, window)
    mean_x = sum_x / window
    mean_y = sum_y / window

    # Exact two-pass second moments. Day-mean centring keeps magnitudes
    # small; the per-window mean then comes from the windowed sums, and the
    # squared deviations accumulate over the 50 slot offsets directly —
    # Σ_j (x[m-j] - μ_w[m])² — so no near-equal subtraction ever happens.
    # A valid window has all `window` bars present (module docstring), so
    # edge-padded lanes can only pollute windows already marked invalid and
    # need no masking.
    # Day-mean centring doubles as the production side of the
    # constant_window pin: a constant window centres to exact zeros ->
    # exactly-zero var/cov (the "degenerate" reading). Under "noise" the
    # centring is skipped and raw f32 accumulation decides, like real
    # polars' raw two-pass variance would in f64.
    if degenerate:
        cx = masked_mean(x, mask)
        cy = masked_mean(y, mask)
        xc = jnp.where(mask, x - cx[..., None], 0.0)
        yc = jnp.where(mask, y - cy[..., None], 0.0)
    else:
        xc = jnp.where(mask, x, 0.0)
        yc = jnp.where(mask, y, 0.0)
    inv_w = 1.0 / window
    mu_x = _windowed_sum(xc, window) * inv_w
    mu_y = _windowed_sum(yc, window) * inv_w

    if impl in ("pallas", "pallas_interpret"):
        from . import rolling_pallas
        s_xx, s_yy, s_xy = rolling_pallas.second_moments(
            xc, yc, mu_x, mu_y, window,
            interpret=(impl == "pallas_interpret"))
    else:
        s_xx, s_yy, s_xy = _second_moments_conv(xc, yc, mu_x, mu_y, window)
    cov = s_xy * inv_w
    var_x = s_xx * inv_w
    var_y = s_yy * inv_w

    return {
        "valid": valid,
        "mean_x": mean_x,
        "mean_y": mean_y,
        "cov": cov,
        "var_x": jnp.maximum(var_x, 0.0),
        "var_y": jnp.maximum(var_y, 0.0),
    }


# --------------------------------------------------------------------------
# parity smoke: `python -m replication_of_minute_frequency_factor_tpu.ops.rolling`
# --------------------------------------------------------------------------


def _f64_reference(x, y, mask, window):
    """Naive f64 windowed moments (numpy, per-window two-pass) — the
    oracle the smoke and the parity sweep compare against."""
    import numpy as np

    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    mask = np.asarray(mask, bool)
    out = {k: np.full(x.shape, np.nan)
           for k in ("mean_x", "mean_y", "cov", "var_x", "var_y")}
    valid = np.zeros(x.shape, bool)
    L = x.shape[-1]
    for i in np.ndindex(x.shape[:-1]):
        for m_ in range(window - 1, L):
            sel = mask[i][m_ - window + 1:m_ + 1]
            if not sel.all():
                continue
            xs = x[i][m_ - window + 1:m_ + 1]
            ys = y[i][m_ - window + 1:m_ + 1]
            valid[i][m_] = True
            out["mean_x"][i][m_] = xs.mean()
            out["mean_y"][i][m_] = ys.mean()
            out["cov"][i][m_] = ((xs - xs.mean()) * (ys - ys.mean())).mean()
            out["var_x"][i][m_] = xs.var()
            out["var_y"][i][m_] = ys.var()
    out["valid"] = valid
    return out


def _smoke(seeds=(0, 739), window=50, rtol=5e-4, atol=1e-6):
    """Quick conv + pallas-interpret parity check against the f64
    reference (run_tests.sh --quick's rolling smoke). Returns a result
    dict; raises AssertionError on a parity failure."""
    import numpy as np

    checks = 0
    for seed in seeds:
        rng = np.random.default_rng(seed)
        shape = (3, 240)
        close = 10.0 * np.exp(np.cumsum(
            rng.standard_normal(shape) * 1e-3, axis=-1))
        low = close * 0.999
        high = close * 1.001
        mask = rng.random(shape) > 0.05
        mask[0] = True
        low[2] = low[2, 0:1]    # constant row: the degenerate-pin case
        high[2] = high[2, 0:1]
        ref = _f64_reference(low, high, mask, window)
        outs = {}
        for impl in ("conv", "pallas_interpret"):
            st = {k: np.asarray(v) for k, v in rolling_window_stats(
                jnp.asarray(low, jnp.float32), jnp.asarray(high, jnp.float32),
                jnp.asarray(mask), window, impl=impl).items()}
            np.testing.assert_array_equal(st["valid"], ref["valid"])
            v = st["valid"]
            for k in ("mean_x", "mean_y", "cov", "var_x", "var_y"):
                np.testing.assert_allclose(st[k][v], ref[k][v],
                                           rtol=rtol, atol=atol)
            # degenerate pin: constant full-coverage windows carry
            # exactly-zero variance (pins.constant_window default)
            assert float(np.max(np.where(v[2], st["var_x"][2], 0.0))) == 0.0
            outs[impl] = st
            checks += 1
        # the two backends must agree far tighter than either vs f64
        v = outs["conv"]["valid"]
        for k in ("cov", "var_x", "var_y"):
            np.testing.assert_allclose(
                outs["pallas_interpret"][k][v], outs["conv"][k][v],
                rtol=1e-5, atol=1e-9)
    return {"ok": True, "checks": checks, "seeds": list(seeds),
            "window": window}


if __name__ == "__main__":
    import json
    import sys

    try:
        result = _smoke()
    except AssertionError as e:
        print(json.dumps({"ok": False,
                          "error": str(e).strip().splitlines()[:6]}))
        sys.exit(1)
    print(json.dumps(result))
