"""Rolling-window regression statistics over the 240-slot minute grid.

The ``mmt_ols_*`` family (reference
MinuteFrequentFactorCalculateMethodsCICC.py:93-376) runs polars
``.rolling(index_column='minute_in_trade', period='50i')``: the window at
trade-minute m covers *index values* (m-50, m] — i.e. slots [m-49, m] on our
dense grid — and windows with fewer than 50 present bars are dropped
(``.filter(pl.len() >= 50)``, :129). Because the interval spans exactly 50
integer slots, a window is kept iff every slot in it holds a bar, which makes
the dense formulation exact: compute stats at every slot via cumulative sums
and mark a window valid when its masked count equals ``window``.

Numerical note: cov/var are shift-invariant, so second moments run on
*day-mean-centred* prices (raw CNY-price squares would eat the f32
mantissa), and windowed sums are a ones-kernel convolution rather than a
difference of cumulative sums: each window is then an independent 50-term
dot product on the MXU, avoiding the prefix-sum cancellation that costs
~3 digits at f32 (observed 5e-3 relative error in ``mmt_ols_qrs`` vs the
f64 oracle with the cumsum formulation; ~1e-6 with the conv one). Raw
windowed means (needed for the reference's beta fallback ``mean_y/mean_x``,
:130-134) use the same path.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from .masked import masked_mean


def _windowed_sum(a, window: int):
    """Inclusive trailing-window sums: out[..., m] = sum(a[..., m-W+1 : m+1])."""
    a = jnp.asarray(a)  # canonicalizes dtype (f64 -> f32 when x64 is off)
    lead, L = a.shape[:-1], a.shape[-1]
    dt = a.dtype if jnp.issubdtype(a.dtype, jnp.floating) else jnp.float32
    x = a.astype(dt).reshape((-1, 1, L))
    k = jnp.ones((1, 1, window), dt)
    out = jax.lax.conv_general_dilated(
        x, k, window_strides=(1,), padding=[(window - 1, 0)],
        dimension_numbers=("NCH", "OIH", "NCH"),
        precision=jax.lax.Precision.HIGHEST)
    return out.reshape(lead + (L,))


def rolling_window_stats(x, y, mask, window: int = 50,
                         impl: str = None) -> Dict[str, jnp.ndarray]:
    """Per-slot trailing-window moments of (x, y) over valid bars.

    Returns dict of ``[..., L]`` arrays:
      ``valid``   — window complete (all ``window`` slots hold bars)
      ``mean_x``/``mean_y`` — raw windowed means
      ``cov``     — windowed covariance, ddof=0
      ``var_x``/``var_y`` — windowed variances, ddof=0

    Stats are only meaningful where ``valid``; other lanes are garbage and
    must be masked by the caller.

    ``impl``: ``'conv'`` (the XLA formulation — the only backend; a
    Pallas VMEM-resident kernel was carried rounds 2-4 but never won a
    tunnel window for a single hardware execution and was dropped per
    the round-3 verdict's prove-or-drop deadline, docs/ROADMAP.md);
    None reads ``Config.rolling_impl``. The parameter stays plumbed
    (registry/pipeline/collectives) so a future kernel slots back in
    without re-threading every call site.
    """
    from replication_of_minute_frequency_factor_tpu import pins

    if impl is None:
        from ..config import get_config
        impl = get_config().rolling_impl
    if impl != "conv":
        raise ValueError(f"unknown rolling_impl {impl!r}; "
                         "expected 'conv'")
    degenerate = pins.reading("constant_window") == "degenerate"
    m = mask.astype(x.dtype)
    xm = jnp.where(mask, x, 0.0)
    ym = jnp.where(mask, y, 0.0)

    n_w = _windowed_sum(m, window)
    valid = n_w > window - 0.5  # robust count equality for float window sums

    sum_x = _windowed_sum(xm, window)
    sum_y = _windowed_sum(ym, window)
    mean_x = sum_x / window
    mean_y = sum_y / window

    # Exact two-pass second moments. Day-mean centring keeps magnitudes
    # small; the per-window mean then comes from the windowed sums, and the
    # squared deviations accumulate over the 50 slot offsets directly —
    # Σ_j (x[m-j] - μ_w[m])² — so no near-equal subtraction ever happens.
    # A valid window has all `window` bars present (module docstring), so
    # rolled-in lanes can only pollute windows already marked invalid and
    # need no masking.
    # Day-mean centring doubles as the production side of the
    # constant_window pin: a constant window centres to exact zeros ->
    # exactly-zero var/cov (the "degenerate" reading). Under "noise" the
    # centring is skipped and raw f32 accumulation decides, like real
    # polars' raw two-pass variance would in f64.
    if degenerate:
        cx = masked_mean(x, mask)
        cy = masked_mean(y, mask)
        xc = jnp.where(mask, x - cx[..., None], 0.0)
        yc = jnp.where(mask, y - cy[..., None], 0.0)
    else:
        xc = jnp.where(mask, x, 0.0)
        yc = jnp.where(mask, y, 0.0)
    inv_w = 1.0 / window
    mu_x = _windowed_sum(xc, window) * inv_w
    mu_y = _windowed_sum(yc, window) * inv_w

    def body(j, acc):
        s_xx, s_yy, s_xy = acc
        d = jnp.roll(xc, j, axis=-1) - mu_x
        e = jnp.roll(yc, j, axis=-1) - mu_y
        return (s_xx + d * d, s_yy + e * e, s_xy + d * e)

    zero = jnp.zeros_like(mu_x)
    s_xx, s_yy, s_xy = jax.lax.fori_loop(
        0, window, body, (zero, zero, zero))
    cov = s_xy * inv_w
    var_x = s_xx * inv_w
    var_y = s_yy * inv_w

    return {
        "valid": valid,
        "mean_x": mean_x,
        "mean_y": mean_y,
        "cov": cov,
        "var_x": jnp.maximum(var_x, 0.0),
        "var_y": jnp.maximum(var_y, 0.0),
    }
