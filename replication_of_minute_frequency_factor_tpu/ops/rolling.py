"""Rolling-window regression statistics over the 240-slot minute grid.

The ``mmt_ols_*`` family (reference
MinuteFrequentFactorCalculateMethodsCICC.py:93-376) runs polars
``.rolling(index_column='minute_in_trade', period='50i')``: the window at
trade-minute m covers *index values* (m-50, m] — i.e. slots [m-49, m] on our
dense grid — and windows with fewer than 50 present bars are dropped
(``.filter(pl.len() >= 50)``, :129). Because the interval spans exactly 50
integer slots, a window is kept iff every slot in it holds a bar, which makes
the dense formulation exact: compute stats at every slot via cumulative sums
and mark a window valid when its masked count equals ``window``.

Numerical note: cov/var are shift-invariant, so second-moment cumsums run on
*day-mean-centred* prices, keeping f32 cumulative sums small on TPU (raw
CNY-price squares summed over 240 slots would eat the f32 mantissa). Raw
windowed means (needed for the reference's beta fallback ``mean_y/mean_x``,
:130-134) come from separate raw cumsums, which are benign.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from .masked import masked_mean


def _windowed_sum(a, window: int):
    """Inclusive trailing-window sums: out[..., m] = sum(a[..., m-W+1 : m+1])."""
    c = jnp.cumsum(a, axis=-1)
    shifted = jnp.concatenate(
        [jnp.zeros(a.shape[:-1] + (window,), a.dtype), c[..., :-window]],
        axis=-1)
    return c - shifted


def rolling_window_stats(x, y, mask, window: int = 50) -> Dict[str, jnp.ndarray]:
    """Per-slot trailing-window moments of (x, y) over valid bars.

    Returns dict of ``[..., L]`` arrays:
      ``valid``   — window complete (all ``window`` slots hold bars)
      ``mean_x``/``mean_y`` — raw windowed means
      ``cov``     — windowed covariance, ddof=0
      ``var_x``/``var_y`` — windowed variances, ddof=0

    Stats are only meaningful where ``valid``; other lanes are garbage and
    must be masked by the caller.
    """
    m = mask.astype(x.dtype)
    xm = jnp.where(mask, x, 0.0)
    ym = jnp.where(mask, y, 0.0)

    n_w = _windowed_sum(m, window)
    valid = n_w == window

    sum_x = _windowed_sum(xm, window)
    sum_y = _windowed_sum(ym, window)
    mean_x = sum_x / window
    mean_y = sum_y / window

    # centred second moments for f32 stability
    cx = masked_mean(x, mask)
    cy = masked_mean(y, mask)
    xc = jnp.where(mask, x - cx[..., None], 0.0)
    yc = jnp.where(mask, y - cy[..., None], 0.0)
    s_xx = _windowed_sum(xc * xc, window)
    s_yy = _windowed_sum(yc * yc, window)
    s_xy = _windowed_sum(xc * yc, window)
    s_x = _windowed_sum(xc, window)
    s_y = _windowed_sum(yc, window)

    inv_w = 1.0 / window
    cov = s_xy * inv_w - (s_x * inv_w) * (s_y * inv_w)
    var_x = s_xx * inv_w - (s_x * inv_w) ** 2
    var_y = s_yy * inv_w - (s_y * inv_w) ** 2

    return {
        "valid": valid,
        "mean_x": mean_x,
        "mean_y": mean_y,
        "cov": cov,
        "var_x": jnp.maximum(var_x, 0.0),
        "var_y": jnp.maximum(var_y, 0.0),
    }
