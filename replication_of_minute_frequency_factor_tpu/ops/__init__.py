"""Masked array ops with polars-compatible reduction semantics.

The dense ``[..., 240]`` day grid carries a boolean validity mask; a cleared
lane is polars *null* (skipped by reductions), while a set lane holding NaN is
polars *NaN* (propagates through means/stds). This null-vs-NaN split is the
load-bearing semantic the whole kernel library builds on (SURVEY.md §7
"hard parts" #1).
"""

from .masked import (  # noqa: F401
    count,
    masked_corr,
    masked_first,
    masked_kurtosis,
    masked_last,
    masked_max,
    masked_mean,
    masked_min,
    masked_product,
    masked_skew,
    masked_std,
    masked_sum,
    masked_var,
    ffill,
    pct_change_valid,
    shift_valid,
)
from .ranking import (  # noqa: F401
    bottomk_threshold,
    masked_order,
    rank_average,
    topk_sum,
    topk_threshold,
)
from .rolling import rolling_window_stats  # noqa: F401
from .segments import segment_stats_by_value, pdf_quantile_rank  # noqa: F401
from .incremental import (  # noqa: F401
    WINDOW_COUNTERS,
    init_inc,
    update_inc,
    update_inc_at,
    window_contains,
)
