"""Pallas TPU kernel for the rolling second-moment pass (VMEM-resident).

The fused conv formulation (:func:`.rolling._second_moments_conv`) asks
XLA to fuse a ``[..., L, W]`` window gather into one Gram reduction; on
TPU that fusion's intermediate traffic is at XLA's discretion. This
kernel removes the discretion: one row-block of the day tensor is loaded
into VMEM once and ALL ``window`` shifted accumulations run against that
resident tile — the 50-term second-moment accumulation never touches HBM
between steps.

Scope is deliberately the second moments only: counts, windowed sums and
means stay on the shared conv path (:mod:`.rolling`), so ``valid`` /
``mean_*`` / ``mu`` are bit-identical across every backend and the
parity surface of this kernel is exactly the three Gram sums.

History: a VMEM rolling kernel was carried rounds 2-4 and dropped under
the round-3 prove-or-drop deadline because no tunnel window ever ran it
on hardware. This reintroduction ships differently: interpret-mode CPU
tests gate parity on every tier-1 run (``tests/test_parity.py``,
``pallas`` marker), production use auto-falls back to conv off-TPU, and
the attribution layer (PR 2) stamps which backend ran into every
manifest — so the kernel cannot linger hardware-unvalidated or silently
claim wins it never produced.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

#: rows per VMEM tile: 5 resident [BLOCK_ROWS, 240] f32 arrays (2 inputs,
#: 3 accumulators) plus shift temporaries stay ~1.5 MB, far under the
#: ~16 MB/core VMEM budget, while a 240-lane tile keeps the VPU fed
BLOCK_ROWS = 128


def available() -> bool:
    """Whether the Pallas TPU lowering path is importable here."""
    try:
        from jax.experimental import pallas  # noqa: F401
        from jax.experimental.pallas import tpu  # noqa: F401
        return True
    except Exception:  # noqa: BLE001 — absence is a supported state
        return False


def _shift_right(a, j: int):
    """out[..., m] = a[..., m-j], zero-filled on the left edge (the same
    only-pollutes-invalid-windows contract as the conv path's padding)."""
    if j == 0:
        return a
    L = a.shape[-1]
    pad = [(0, 0)] * (a.ndim - 1) + [(j, 0)]
    return jnp.pad(a[..., :L - j], pad)


def _moment_kernel(window: int, xc_ref, yc_ref, mux_ref, muy_ref,
                   sxx_ref, syy_ref, sxy_ref):
    """One [block, L] tile: Σ_j d_j², Σ_j e_j², Σ_j d_j·e_j with
    d_j = shift(xc, j) - μ_x. The j-loop is unrolled at trace time
    (``window`` is static) and every operand is VMEM-resident."""
    xc = xc_ref[...]
    yc = yc_ref[...]
    mu_x = mux_ref[...]
    mu_y = muy_ref[...]
    s_xx = jnp.zeros_like(xc)
    s_yy = jnp.zeros_like(xc)
    s_xy = jnp.zeros_like(xc)
    for j in range(window):
        d = _shift_right(xc, j) - mu_x
        e = _shift_right(yc, j) - mu_y
        s_xx = s_xx + d * d
        s_yy = s_yy + e * e
        s_xy = s_xy + d * e
    sxx_ref[...] = s_xx
    syy_ref[...] = s_yy
    sxy_ref[...] = s_xy


def second_moments(xc, yc, mu_x, mu_y, window: int,
                   interpret: bool = False,
                   block_rows: int = BLOCK_ROWS):
    """VMEM-resident ``(s_xx, s_yy, s_xy)`` for day-centred inputs.

    Inputs are the conv path's own centred series and window means
    (``[..., L]`` each, any leading shape); outputs match. ``interpret``
    runs the identical kernel on the Pallas interpreter — CPU-safe, the
    parity-test path. Leading dims flatten to rows; rows pad up to the
    grid's block multiple and the pad rows are sliced back off (their
    zero inputs produce zeros — never read).
    """
    from jax.experimental import pallas as pl

    xc = jnp.asarray(xc)
    yc = jnp.asarray(yc)
    lead, L = xc.shape[:-1], xc.shape[-1]
    dt = xc.dtype if jnp.issubdtype(xc.dtype, jnp.floating) else jnp.float32
    rows = 1
    for n in lead:
        rows *= n
    flat = []
    for a in (xc, yc, mu_x, mu_y):
        flat.append(jnp.asarray(a, dt).reshape((rows, L)))
    block = max(8, min(block_rows, rows))  # >=8 sublanes for f32 tiles
    pad = (-rows) % block
    if pad:
        flat = [jnp.pad(a, ((0, pad), (0, 0))) for a in flat]
    grid = ((rows + pad) // block,)
    spec = pl.BlockSpec((block, L), lambda i: (i, 0))
    shape = jax.ShapeDtypeStruct((rows + pad, L), dt)
    s_xx, s_yy, s_xy = pl.pallas_call(
        functools.partial(_moment_kernel, window),
        grid=grid,
        in_specs=[spec] * 4,
        out_specs=[spec] * 3,
        out_shape=[shape] * 3,
        interpret=interpret,
    )(*flat)
    return (s_xx[:rows].reshape(lead + (L,)),
            s_yy[:rows].reshape(lead + (L,)),
            s_xy[:rows].reshape(lead + (L,)))
