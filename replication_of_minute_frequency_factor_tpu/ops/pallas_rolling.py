"""Pallas TPU kernel for the rolling 50-bar moment family.

The ``mmt_ols_*`` kernels need, per minute slot, trailing-window count,
means, covariance and variances of (low, high) — the hottest compute in
the 58-factor graph. The XLA formulation (ops/rolling.py) is precise but
memory-bound: the exact two-pass moments run a 50-iteration roll loop,
each iteration streaming three ``[N, 240]`` arrays through HBM (~50x6
array passes per batch).

This kernel keeps one row-block of the day tensor resident in VMEM and
does everything locally:

  * windowed counts/sums as banded matmuls — a ``[240, 240]`` constant
    lower-banded ones matrix on the MXU replaces the 1-wide convolution
    (conv with channel=1 maps poorly onto the 128x128 systolic array);
  * the exact two-pass deviation loop (``sum_j (x[m-j] - mu_w[m])^2``)
    as an in-VMEM ``fori_loop`` over lane rotations — no HBM round-trips
    between iterations.

Numerics are identical to the XLA path by construction: same banded-sum
windowing (HIGHEST-precision dots), same day-mean centring, same
two-pass deviation accumulation, so the conv-vs-pallas parity test pins
them to ~1 ulp.

Disabled by default (``Config.rolling_impl = 'conv'``) until profiled
faster on real hardware; tests run the interpreter.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU memory spaces; absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    _VMEM = None

N_SLOTS = 240
_BLOCK_ROWS = 256


def resolve_interpret(interpret: Optional[bool] = None) -> bool:
    """Resolve the pallas interpret flag: compile on Mosaic-capable
    platforms ('tpu', and the tunnelled chip's experimental 'axon'),
    interpret everywhere else.

    The earlier ``!= "tpu"`` autodetect silently selected interpret
    mode on the axon-registered hardware the kernel was built for
    (ADVICE r3, high) — timing the emulator and banking bogus speedups.
    An allowlist rather than ``== "cpu"`` because a GPU backend can't
    lower the TPU-targeted kernel either and must keep interpreting.
    Callers that bank results (benchmarks/tpu_session.py) record this
    resolved value and refuse to bank interpret runs."""
    if interpret is None:
        return jax.default_backend() not in ("tpu", "axon")
    return interpret


def _banded(window: int, n: int = N_SLOTS) -> np.ndarray:
    """A[s, m] = 1 iff slot s lies in m's trailing window (m-W, m]."""
    s = np.arange(n)[:, None]
    m = np.arange(n)[None, :]
    return ((s <= m) & (s > m - window)).astype(np.float32)


def _kernel(a_ref, x_ref, y_ref, m_ref,
            cnt_ref, mx_ref, my_ref, cov_ref, vx_ref, vy_ref,
            *, window: int):
    a = a_ref[...]
    m = m_ref[...]
    x = x_ref[...] * m
    y = y_ref[...] * m

    dot = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)

    inv_w = 1.0 / window
    cnt_ref[...] = dot(m, a)
    mx_ref[...] = dot(x, a) * inv_w
    my_ref[...] = dot(y, a) * inv_w

    # day-mean centring (keeps magnitudes small; see ops/rolling.py)
    n_day = jnp.maximum(jnp.sum(m, axis=-1, keepdims=True), 1.0)
    xc = (x - jnp.sum(x, axis=-1, keepdims=True) / n_day) * m
    yc = (y - jnp.sum(y, axis=-1, keepdims=True) / n_day) * m
    mu_x = dot(xc, a) * inv_w
    mu_y = dot(yc, a) * inv_w

    def body(j, acc):
        s_xx, s_yy, s_xy = acc
        d = jnp.roll(xc, j, axis=-1) - mu_x
        e = jnp.roll(yc, j, axis=-1) - mu_y
        return (s_xx + d * d, s_yy + e * e, s_xy + d * e)

    zero = jnp.zeros_like(mu_x)
    s_xx, s_yy, s_xy = jax.lax.fori_loop(0, window, body, (zero, zero, zero))
    cov_ref[...] = s_xy * inv_w
    vx_ref[...] = jnp.maximum(s_xx * inv_w, 0.0)
    vy_ref[...] = jnp.maximum(s_yy * inv_w, 0.0)


def rolling_window_stats_pallas(
        x, y, mask, window: int = 50,
        interpret: Optional[bool] = None) -> Dict[str, jnp.ndarray]:
    """Drop-in for :func:`ops.rolling.rolling_window_stats` (same contract:
    stats are garbage outside ``valid`` lanes and must be masked)."""
    interpret = resolve_interpret(interpret)
    lead = x.shape[:-1]
    n = int(np.prod(lead)) if lead else 1
    xf = jnp.reshape(x.astype(jnp.float32), (n, N_SLOTS))
    yf = jnp.reshape(y.astype(jnp.float32), (n, N_SLOTS))
    mf = jnp.reshape(mask.astype(jnp.float32), (n, N_SLOTS))
    pad = (-n) % _BLOCK_ROWS
    if pad:
        xf, yf, mf = (jnp.pad(v, ((0, pad), (0, 0))) for v in (xf, yf, mf))
    rows = n + pad
    a = jnp.asarray(_banded(window))

    row_spec = pl.BlockSpec((_BLOCK_ROWS, N_SLOTS), lambda i: (i, 0),
                            **({} if _VMEM is None
                               else {"memory_space": _VMEM}))
    a_spec = pl.BlockSpec((N_SLOTS, N_SLOTS), lambda i: (0, 0),
                          **({} if _VMEM is None
                             else {"memory_space": _VMEM}))
    shape = jax.ShapeDtypeStruct((rows, N_SLOTS), jnp.float32)
    outs = pl.pallas_call(
        functools.partial(_kernel, window=window),
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[a_spec, row_spec, row_spec, row_spec],
        out_specs=[row_spec] * 6,
        out_shape=[shape] * 6,
        interpret=interpret,
    )(a, xf, yf, mf)
    cnt, mean_x, mean_y, cov, var_x, var_y = (
        jnp.reshape(o[:n], lead + (N_SLOTS,)) for o in outs)
    return {
        "valid": cnt > window - 0.5,
        "mean_x": mean_x,
        "mean_y": mean_y,
        "cov": cov,
        "var_x": var_x,
        "var_y": var_y,
    }
