"""Ranking and top-k threshold ops on the masked minute grid.

``rank_average`` reproduces polars ``Expr.rank(method='average')`` (used both
by ``doc_pdf*`` chip factors — reference
MinuteFrequentFactorCalculateMethodsCICC.py:1016 — and by Spearman rank-IC in
evaluation, Factor.py:178-182). ``topk_threshold`` reproduces the
``volume.top_k(k).min()`` / ``bottom_k(k).max()`` cut used by the
``mmt_*VolumeRet`` family (:389-397,417-421).

Everything is sort-based over the trailing axis (240 lanes or a ticker
cross-section) — small dense sorts that XLA lowers well on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .masked import cummax_last

_NAN = jnp.nan


def _group_bounds(new_group):
    """Per-lane start/end index of the tie-group each sorted lane belongs to.

    ``new_group[..., i]`` is True when sorted lane i starts a new tie-group.
    """
    L = new_group.shape[-1]
    idx = jnp.arange(L)
    start = cummax_last(jnp.where(new_group, idx, -1))
    # end of my group = (next group's start) - 1; compute via reversed scan
    is_end = jnp.concatenate(
        [new_group[..., 1:], jnp.ones(new_group.shape[:-1] + (1,), bool)],
        axis=-1)
    rev = is_end[..., ::-1]
    nearest_end_rev = cummax_last(jnp.where(rev, jnp.arange(L), -1))
    end = (L - 1 - nearest_end_rev)[..., ::-1]
    return start, end


def masked_order(x, mask):
    """Stable ascending sort order with invalid lanes strictly last.

    Two-key lexsort (validity primary, value secondary), so a genuine
    ``+inf`` in a valid lane still sorts before every invalid lane instead
    of colliding with a sentinel.
    """
    key = jnp.where(mask, x, 0.0)  # neutralise NaN/garbage in invalid lanes
    return jnp.lexsort((key, ~mask), axis=-1)


def rank_average(x, mask):
    """Average-tie ranks (1-based) among valid lanes; NaN elsewhere.

    Tie groups occupy consecutive positions after a stable sort, so the
    average rank of a group spanning sorted positions [s, e] is
    ((s+1) + (e+1)) / 2 — no segment-sum needed.
    """
    L = x.shape[-1]
    order = masked_order(x, mask)
    sx = jnp.take_along_axis(jnp.where(mask, x, 0.0), order, axis=-1)
    sm = jnp.take_along_axis(mask, order, axis=-1)
    new_group = jnp.concatenate(
        [jnp.ones(x.shape[:-1] + (1,), bool),
         (sx[..., 1:] != sx[..., :-1]) | (sm[..., 1:] != sm[..., :-1])],
        axis=-1)
    start, end = _group_bounds(new_group)
    avg = (start + end).astype(jnp.float32) / 2.0 + 1.0
    inv = jnp.argsort(order, axis=-1, stable=True)
    ranks = jnp.take_along_axis(avg, inv, axis=-1)
    return jnp.where(mask, ranks, _NAN)


def topk_threshold(x, mask, k: int, largest: bool = True):
    """k-th largest (smallest) valid value; all-valid extreme when n < k.

    Matches polars ``x.top_k(k).min()`` (``bottom_k(k).max()``), which
    returns min/max over however many elements exist when the group is
    shorter than k. NaN when the group is empty.
    """
    k = min(k, x.shape[-1])
    key = jnp.where(mask, x, -jnp.inf if largest else jnp.inf)
    if not largest:
        key = -key
    vals, _ = jax.lax.top_k(key, k)  # descending
    n = jnp.sum(mask, axis=-1)
    kk = jnp.minimum(k, jnp.maximum(n, 1)) - 1
    thr = jnp.take_along_axis(vals, kk[..., None], axis=-1)[..., 0]
    if not largest:
        thr = -thr
    return jnp.where(n > 0, thr, _NAN)


def bottomk_threshold(x, mask, k: int):
    return topk_threshold(x, mask, k, largest=False)


def topk_sum(x, mask, k: int):
    """Sum of the k largest valid values (all of them when n < k) —
    polars ``x.top_k(k).sum()`` (doc_vol*_ratio, reference :1153-1156)."""
    k = min(k, x.shape[-1])
    key = jnp.where(mask, x, -jnp.inf)
    vals, _ = jax.lax.top_k(key, k)
    n = jnp.sum(mask, axis=-1)
    take = jnp.arange(k) < jnp.minimum(n, k)[..., None]
    s = jnp.sum(jnp.where(take, vals, 0.0), axis=-1)
    return jnp.where(n > 0, s, _NAN)
