"""Group-by-exact-value segment ops for the chip (volume-at-price) factors.

The ``doc_*`` family (reference
MinuteFrequentFactorCalculateMethodsCICC.py:937-1201) groups each stock's
volume shares by exact end-of-day-relative return value, then takes moments
of the per-group sums, or walks the cumulative distribution to a quantile.

On the dense grid this becomes: sort the 240 lanes by value, detect tie-group
boundaries, and read per-segment sums off a cumulative-weight array at the
segment *end* positions. Moments over segments then reuse the ordinary masked
reductions with "is a segment end" as the mask — no scatter/segment_sum
needed, which keeps everything a fused sort+cumsum on TPU.

Ordering note (SURVEY.md §2.5 Q7): the reference's ``cum_sum`` runs in
polars' non-deterministic group-output order; we fix the order to ascending
value (= ascending rank), the intended semantics, and the numpy oracle
matches this choice.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .masked import cummax_last, masked_kurtosis, masked_skew

_NAN = jnp.nan


def _sorted_segments(values, weights, mask):
    """Sort lanes by value; return per-lane segment-end flags and segment sums.

    Returns ``(sv, seg_sum, is_end, cumw)`` where lanes are in
    ascending-value order (invalid lanes strictly last via two-key sort, so
    valid ``+inf`` values keep their own segment), ``is_end`` marks the last
    lane of each valid tie-group, ``seg_sum`` holds (at end lanes) the summed
    weight of that group, and ``cumw`` is the running weight cumsum.
    """
    from .ranking import masked_order

    order = masked_order(values, mask)
    sv = jnp.take_along_axis(jnp.where(mask, values, 0.0), order, axis=-1)
    sw = jnp.take_along_axis(jnp.where(mask, weights, 0.0), order, axis=-1)
    smask = jnp.take_along_axis(mask, order, axis=-1)

    L = values.shape[-1]
    new_group = jnp.concatenate(
        [jnp.ones(values.shape[:-1] + (1,), bool),
         (sv[..., 1:] != sv[..., :-1]) | (smask[..., 1:] != smask[..., :-1])],
        axis=-1)
    is_end = jnp.concatenate(
        [new_group[..., 1:], jnp.ones(values.shape[:-1] + (1,), bool)],
        axis=-1) & smask

    cumw = jnp.cumsum(sw, axis=-1)
    idx = jnp.arange(L)
    start = cummax_last(jnp.where(new_group, idx, -1))
    prev_cum = jnp.where(
        start > 0,
        jnp.take_along_axis(cumw, jnp.maximum(start - 1, 0), axis=-1),
        0.0)
    seg_sum = cumw - prev_cum
    return sv, seg_sum, is_end, cumw


def segment_stats_by_value(values, weights, mask) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(skew, kurtosis) of per-unique-value weight sums — ``doc_skew`` /
    ``doc_kurt`` / ``doc_std``-as-coded (reference :948-1001)."""
    _, seg_sum, is_end, _ = _sorted_segments(values, weights, mask)
    return masked_skew(seg_sum, is_end), masked_kurtosis(seg_sum, is_end)


def pdf_quantile_rank(values, weights, mask, threshold: float):
    """First (lowest-value) segment whose cumulative weight exceeds
    ``threshold``; returns that segment's ``values`` entry.

    Matches ``doc_pdf*`` (reference :1022-1027) under the ascending-order
    resolution of quirk Q7: with non-negative weights the end-of-segment
    cumulative sums are non-decreasing in value order, so "min rank among
    qualifying" equals "first segment whose cumulative share crosses the
    threshold". NaN when nothing qualifies (e.g. NaN shares from a
    zero-volume day).
    """
    sv, _, is_end, cumw = _sorted_segments(values, weights, mask)
    qualify = is_end & (cumw > threshold)
    any_q = jnp.any(qualify, axis=-1)
    first = jnp.argmax(qualify, axis=-1)
    val = jnp.take_along_axis(sv, first[..., None], axis=-1)[..., 0]
    return jnp.where(any_q, val, _NAN)
