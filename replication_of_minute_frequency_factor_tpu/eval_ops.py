"""Device-side evaluation kernels: per-date cross-sectional statistics.

The hot loop of evaluation (reference Factor.py:172-182, :284-292) is a
reduction *across tickers for every date* — here one ``vmap`` over the date
axis of dense ``[dates, tickers]`` matrices (SURVEY.md §3.2). Under a
sharded ticker axis the same math runs through
:mod:`.parallel.collectives`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .ops import masked_corr, rank_average


@jax.jit
def ic_series(exposure, fwd_ret, valid):
    """Per-date Pearson IC and Spearman rank-IC.

    exposure, fwd_ret: ``[dates, tickers]``; valid: both present and non-NaN
    (reference drops NaN exposures before correlating, Factor.py:167-169).
    Returns ``(ic [dates], rank_ic [dates])`` — NaN where a date has <2
    valid tickers or zero variance.
    """
    ic = masked_corr(exposure, fwd_ret, valid)
    rx = rank_average(exposure, valid)
    ry = rank_average(fwd_ret, valid)
    # rank_average leaves NaN outside ``valid``; neutralise before corr
    rank_ic = masked_corr(jnp.where(valid, rx, 0.0),
                          jnp.where(valid, ry, 0.0), valid)
    return ic, rank_ic


def qcut_labels(exposure, valid, group_num: int, nan_lanes=None):
    """Per-date quantile-bucket labels 0..group_num-1 (NaN-safe).

    Matches polars ``qcut(group_num, allow_duplicates=True)`` over each date
    (Factor.py:284-292): bucket edges are the linear-interpolated quantiles
    of that date's valid exposures; duplicate edges collapse (a value never
    lands in an empty duplicate bucket because ``searchsorted`` on the
    sorted edge list is right-continuous). Invalid lanes get -1.

    ``nan_lanes`` marks lanes whose exposure is a value-NaN (present but
    not finite). Under the default ``pins.READINGS['qcut_nan'] ==
    'exclude'`` reading they stay -1 (excluded, like the shim's
    NaN->null); under the alternative ``'top_bin'`` reading they join
    the last bucket, polars' total-float-order possibility the
    reference's unfiltered group_test would expose (Factor.py:280-292).
    """
    from replication_of_minute_frequency_factor_tpu import pins

    lab = _qcut_labels_jit(exposure, valid, group_num)
    if nan_lanes is not None and pins.reading("qcut_nan") == "top_bin":
        lab = jnp.where(jnp.asarray(nan_lanes), group_num - 1, lab)
    return lab


@functools.partial(jax.jit, static_argnames=("group_num",))
def _qcut_labels_jit(exposure, valid, group_num: int):
    qs = jnp.linspace(0.0, 1.0, group_num + 1)[1:-1]

    def one_date(x, m):
        n = jnp.sum(m)
        # quantiles over valid lanes via sorted gather at fractional index
        order = jnp.argsort(jnp.where(m, x, jnp.inf))
        sx = jnp.where(m, x, 0.0)[order]
        pos = qs * jnp.maximum(n - 1, 0)
        lo = jnp.floor(pos).astype(jnp.int32)
        hi = jnp.ceil(pos).astype(jnp.int32)
        frac = pos - lo
        # np.quantile's exact _lerp, branch included: a + t*(b-a) below
        # t=0.5, b - (b-a)*(1-t) at or above. The two-product form
        # lo*(1-frac) + hi*frac is inexact in f32 even when both
        # endpoints are EQUAL (fuzz seed 6290: a [-0.1, -0.1]
        # cross-section produced an edge one ulp below the tied value,
        # shifting its bucket), and the single-sided a + t*(b-a) still
        # sits one ulp off numpy for frac >= 0.5 with distinct
        # endpoints — only the two-sided form reproduces the oracle's
        # edges bit-for-bit (both branches are exact for d == 0).
        d = sx[hi] - sx[lo]
        edges = jnp.where(frac >= 0.5,
                          sx[hi] - d * (1 - frac),
                          sx[lo] + frac * d)
        # right-closed buckets like polars/pandas qcut: x <= edge_i -> bucket i
        lab = jnp.sum(x[:, None] > edges[None, :], axis=-1)
        return jnp.where(m & (n > 0), lab, -1)

    return jax.vmap(one_date)(exposure, valid)


@jax.jit
def coverage_counts(valid):
    """Per-date count of usable exposures (Factor.py:92-105)."""
    return jnp.sum(valid, axis=-1)


def decile_spread(exposure, fwd_ret, valid, group_num: int = 5):
    """Per-date long-short spread of the exposure's quantile buckets.

    ``exposure``/``fwd_ret``/``valid``: ``[dates, tickers]``. Buckets
    come from :func:`_qcut_labels_jit` (the production qcut core —
    reused, not reimplemented, so a discovered factor's backtest
    buckets can never drift from the serving layer's decile answers);
    the spread is ``mean(fwd_ret | top bucket) - mean(fwd_ret | bottom
    bucket)`` per date, NaN where either end bucket is empty. This is
    the decile half of the research fitness graph
    (:mod:`.research.fitness`): IC says *monotone association*, the
    end-bucket spread says *tradeable separation* — a factor can have
    a decent IC and an untradeably flat tail.
    """
    labels = _qcut_labels_jit(exposure, valid, group_num)  # [D, T]
    onehot = labels[..., None] == jnp.arange(group_num)    # [D, T, G]
    okr = onehot & (valid & jnp.isfinite(fwd_ret))[..., None]
    n = jnp.sum(okr, axis=-2)                              # [D, G]
    s = jnp.sum(jnp.where(okr, fwd_ret[..., None], 0.0), axis=-2)
    mean_ret = jnp.where(n > 0, s / jnp.maximum(n, 1), jnp.nan)
    return mean_ret[..., -1] - mean_ret[..., 0]            # [D]
