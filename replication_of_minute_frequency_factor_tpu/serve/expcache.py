"""Device-resident exposure cache: LRU under an explicit byte budget.

A served day-range's computed block (``[F, days, tickers]`` exposures
plus the daily close / validity planes the IC and decile queries
derive from) stays in device memory so a repeat query costs a cache
lookup instead of an encode + transfer + fused-graph dispatch. HBM is
the scarce resource: entries are accounted by their device ``nbytes``
and evicted least-recently-used when the budget would overflow, with
the evicted handles deleted so the backend reclaims the memory
immediately instead of at GC time.

Single-consumer contract: the request loop (one worker thread) is the
only reader — an entry returned by ``get``/``put`` is used before the
next ``put``, so eviction-time deletion can never pull a buffer out
from under a live query. Counters: ``serve.cache{outcome=hit|miss}``,
``serve.cache_evictions``, ``serve.cache_oversize``; gauges:
``serve.cache_bytes``, ``serve.cache_entries``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Hashable, Optional


def entry_nbytes(entry: Dict[str, object]) -> int:
    """Device bytes held by a block entry (sum over its arrays)."""
    return int(sum(int(getattr(v, "nbytes", 0) or 0)
                   for v in entry.values()))


class DeviceExposureCache:
    """LRU ``key -> {name: device array}`` map bounded by device bytes.

    ``byte_budget <= 0`` disables caching entirely (every ``get`` is a
    miss, ``put`` stores nothing) — the knob for a measurement run that
    wants every request to pay the dispatch.
    """

    def __init__(self, byte_budget: int, telemetry=None,
                 free_on_evict: bool = True):
        self.byte_budget = int(byte_budget)
        self.free_on_evict = free_on_evict
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Hashable, tuple]" = OrderedDict()
        self._bytes = 0
        self._telemetry = telemetry

    def _tel(self):
        if self._telemetry is not None:
            return self._telemetry
        from ..telemetry import get_telemetry
        return get_telemetry()

    # --- stats ----------------------------------------------------------
    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def _gauges(self) -> None:
        tel = self._tel()
        tel.gauge("serve.cache_bytes", self._bytes)
        tel.gauge("serve.cache_entries", len(self._entries))
        # budget + headroom ride along (ISSUE 8): with the
        # device.hbm_* watermarks they answer "is the LRU budget sized
        # to the memory actually available" from one scrape
        tel.gauge("serve.cache_budget_bytes", self.byte_budget)
        tel.gauge("serve.cache_headroom_bytes",
                  max(0, self.byte_budget - self._bytes))

    # --- read/write -----------------------------------------------------
    def get(self, key: Hashable) -> Optional[Dict[str, object]]:
        tel = self._tel()
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
        if hit is None:
            tel.counter("serve.cache", outcome="miss")
            return None
        tel.counter("serve.cache", outcome="hit")
        return hit[0]

    def put(self, key: Hashable,
            entry: Dict[str, object]) -> Dict[str, object]:
        """Insert (or refresh) ``entry``, evicting LRU entries until it
        fits. An entry larger than the whole budget is returned
        UNCACHED (``serve.cache_oversize``) — caching it would evict
        everything and still overflow."""
        tel = self._tel()
        nbytes = entry_nbytes(entry)
        evicted = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            if nbytes > self.byte_budget:
                tel.counter("serve.cache_oversize")
                self._gauges()
                return entry
            while self._entries and self._bytes + nbytes > self.byte_budget:
                _, (dead, dead_bytes) = self._entries.popitem(last=False)
                self._bytes -= dead_bytes
                evicted.append(dead)
            self._entries[key] = (entry, nbytes)
            self._bytes += nbytes
            self._gauges()
        for dead in evicted:
            tel.counter("serve.cache_evictions")
            if self.free_on_evict:
                _delete_entry(dead)
        return entry

    def clear(self) -> None:
        with self._lock:
            dead = [e for e, _ in self._entries.values()]
            self._entries.clear()
            self._bytes = 0
            self._gauges()
        if self.free_on_evict:
            for e in dead:
                _delete_entry(e)


def _delete_entry(entry: Dict[str, object]) -> None:
    """Release an evicted block's device buffers now (best-effort): the
    LRU exists to bound HBM, so reclamation must not wait for Python
    GC of whatever references linger."""
    for v in entry.values():
        try:
            deleted = getattr(v, "is_deleted", None)
            if callable(deleted) and not deleted():
                v.delete()
        except Exception:  # noqa: BLE001 — freeing is best-effort
            pass
