"""First-party result-wire client decoder (ISSUE 20).

The serving edge answers ``POST /v1/query`` + ``Accept:
application/x-mff-wire`` with the packed result-wire payload VERBATIM
(framed by :func:`..data.result_wire.pack_frame`, one frame per
buffered answer, one frame per chunk of a streamed range answer).
This module is the other half of that contract:

* :func:`decode_answer` — an IN-PROCESS wire answer dict (what
  ``ServeClient.factors_wire`` gets back from the queue) to
  ``(exposures [F, D, T] f32, meta)``.
* :func:`decode_frames` — an HTTP response body of one or more frames
  to the same ``(exposures, meta)``; chunked range answers arrive in
  COMPLETION order and reassemble here by each frame's ``start``.
* :class:`WireClient` — a persistent keep-alive HTTP/1.1 client used
  by ``bench.py``'s load generators and the fleet tooling; one TCP
  connection serves any number of queries (the pre-ISSUE-20 bench
  paid connect+teardown per request).

GL-A3 note: everything here operates on ALREADY-FETCHED host bytes
(``np.frombuffer`` over a socket read); the device fetch happened on
the server side at its declared boundary. The module is in the serve
layer's host-sync scope and stays sync-free by construction.
"""

from __future__ import annotations

import http.client
import json
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from ..data import result_wire as _rw
from .http import WIRE_CONTENT_TYPE


class WireError(RuntimeError):
    """A non-200 (or non-wire) answer to a wire query. Carries the
    HTTP ``status``, the decoded error ``doc`` and the parsed
    ``retry_after`` hint (seconds, None when absent) so callers can
    honor the shed/quota backoff contract without re-parsing."""

    def __init__(self, status: int, doc: dict,
                 retry_after: Optional[float] = None):
        super().__init__(f"wire query failed: HTTP {status} "
                         f"{doc.get('error', '')}".strip())
        self.status = status
        self.doc = doc
        self.retry_after = retry_after


def _strip_verdict(verdict: dict) -> dict:
    # the sidx plane is for parity gates, not JSON-able client meta
    return {k: v for k, v in verdict.items() if k != "sidx"}


def decode_answer(ans: dict, telemetry=None
                  ) -> Tuple[np.ndarray, Dict[str, Any]]:
    """One in-process wire answer dict -> ``(exposures, meta)``."""
    buf = ans["payload"]
    if not isinstance(buf, np.ndarray):
        buf = np.frombuffer(buf, dtype=np.uint8)
    names = ans.get("names")
    out, verdict = _rw.decode_block(
        buf, ans["n_factors"], ans["days"], ans["tickers"],
        ans["spill_rows"], telemetry=telemetry, names=names)
    meta = {
        "start": ans.get("start"), "end": ans.get("end"),
        "n_factors": int(ans["n_factors"]), "days": int(ans["days"]),
        "tickers": int(ans["tickers"]),
        "spill_rows": int(ans["spill_rows"]),
        "names": list(names or ()), "frames": 1,
        "payload_bytes": int(buf.nbytes),
        "verdict": _strip_verdict(verdict),
    }
    return out, meta


def decode_frames(body: bytes, telemetry=None,
                  names: Optional[Sequence[str]] = None
                  ) -> Tuple[np.ndarray, Dict[str, Any]]:
    """An HTTP wire body (>= 1 frames) -> ``(exposures, meta)``.

    Frames of a chunked range answer flush in completion order; each
    frame's header carries its ``(start, end)`` day range, so
    reassembly sorts by ``start`` and concatenates on the day axis —
    byte-identical to the buffered answer for the same range."""
    blocks = []
    for meta, payload in _rw.iter_frames(body):
        out, verdict = _rw.decode_block(
            payload, meta["n_factors"], meta["days"], meta["tickers"],
            meta["spill_rows"], telemetry=telemetry, names=names)
        blocks.append((meta, out, verdict))
    if not blocks:
        raise ValueError("wire body carried no frames")
    first = blocks[0][0]
    for meta, _out, _v in blocks[1:]:
        if (meta["n_factors"], meta["tickers"]) \
                != (first["n_factors"], first["tickers"]):
            raise ValueError("frames disagree on block geometry: "
                             f"{meta} vs {first}")
    blocks.sort(key=lambda b: b[0]["start"])
    out = (blocks[0][1] if len(blocks) == 1
           else np.concatenate([b[1] for b in blocks], axis=1))
    meta = {
        "start": blocks[0][0]["start"], "end": blocks[-1][0]["end"],
        "n_factors": first["n_factors"], "days": int(out.shape[1]),
        "tickers": first["tickers"],
        "spill_rows": first["spill_rows"],
        "frames": len(blocks),
        "payload_bytes": sum(b[0]["payload_bytes"] for b in blocks),
        "ranges": [(b[0]["start"], b[0]["end"]) for b in blocks],
        "verdict": _strip_verdict(blocks[0][2]) if len(blocks) == 1
        else {"frames": [_strip_verdict(b[2]) for b in blocks]},
    }
    return out, meta


class WireClient:
    """A persistent keep-alive HTTP client for either front door.

    One ``http.client.HTTPConnection`` is reused across requests
    (reconnecting ONCE on a stale keep-alive socket); ``tenant`` goes
    out as ``X-Tenant`` on every request so the edge's token buckets
    meter the right principal. Not thread-safe — bench gives each
    load-generator thread its own instance."""

    def __init__(self, host: str, port: int, timeout: float = 60.0,
                 tenant: Optional[str] = None, telemetry=None):
        self.host = host
        self.port = int(port)
        self.timeout = timeout
        self.tenant = tenant
        self.telemetry = telemetry
        self._conn: Optional[http.client.HTTPConnection] = None

    # -- transport ----------------------------------------------------

    def _connection(self) -> http.client.HTTPConnection:
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout)
        return self._conn

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def request(self, method: str, path: str, body: bytes = None,
                headers: Optional[Dict[str, str]] = None
                ) -> Tuple[int, Dict[str, str], bytes]:
        """One request over the persistent connection ->
        ``(status, lowercased headers, body)``."""
        hdrs = dict(headers or ())
        if self.tenant:
            hdrs.setdefault("X-Tenant", self.tenant)
        last: Optional[Exception] = None
        for attempt in range(2):
            conn = self._connection()
            try:
                conn.request(method, path, body=body, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
                return (resp.status,
                        {k.lower(): v for k, v in resp.getheaders()},
                        data)
            except (http.client.HTTPException, OSError) as e:
                # a stale keep-alive socket (server reaped the idle
                # connection) fails exactly once; reconnect and retry
                last = e
                self.close()
        raise last  # type: ignore[misc]

    # -- JSON surface -------------------------------------------------

    def get_json(self, path: str) -> Tuple[int, Any]:
        status, _hdrs, data = self.request("GET", path)
        return status, json.loads(data)

    def post_json(self, path: str, doc: dict,
                  headers: Optional[Dict[str, str]] = None
                  ) -> Tuple[int, Dict[str, str], bytes]:
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or ())
        return self.request("POST", path,
                            body=json.dumps(doc).encode(),
                            headers=hdrs)

    def query_json(self, doc: dict) -> Tuple[int, Any]:
        status, _hdrs, data = self.post_json("/v1/query", doc)
        return status, json.loads(data)

    # -- the wire -----------------------------------------------------

    def query_wire(self, start: int, end: int, *,
                   chunk_days: Optional[int] = None
                   ) -> Tuple[np.ndarray, Dict[str, Any]]:
        """A wire-encoded full-set factors query ->
        ``(exposures [F, D, T] f32, meta)``. ``chunk_days`` asks the
        edge to stream the range as framed chunks (reassembled here);
        sheds and quota refusals raise :class:`WireError` with the
        server's ``Retry-After`` hint."""
        doc: Dict[str, Any] = {"kind": "factors", "start": int(start),
                               "end": int(end)}
        if chunk_days:
            doc["chunk_days"] = int(chunk_days)
        status, hdrs, data = self.post_json(
            "/v1/query", doc, headers={"Accept": WIRE_CONTENT_TYPE})
        if status != 200:
            try:
                err = json.loads(data)
            except (ValueError, json.JSONDecodeError):
                err = {"error": data[:200].decode("latin-1")}
            ra = hdrs.get("retry-after")
            raise WireError(status, err,
                            float(ra) if ra is not None else None)
        if WIRE_CONTENT_TYPE not in hdrs.get("content-type", ""):
            raise WireError(status, {"error": "server answered "
                                              "JSON where wire was "
                                              "negotiated"})
        return decode_frames(data, telemetry=self.telemetry)
