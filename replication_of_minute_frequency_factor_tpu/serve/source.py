"""Data sources for the factor service: who owns the minute bars.

A source holds (or can produce) the dense ``[days, tickers, 240, 5]``
bar tensor + validity mask the serve engine encodes into blocks.
Day-ranges are addressed by integer index into ``days`` — the service's
coalescing key — with the day labels and ticker codes exposed for
responses.

Host-side module, but deliberately written without host-sync calls:
everything here is numpy-on-numpy (graftlint GL-A3 covers ``serve/``,
and this module needs no boundary-policy entry).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


class SyntheticSource:
    """Deterministic synthetic year (bench's batch generator shape):
    seeded once, fully materialized in host RAM — the bench/test/demo
    source, sized by the caller."""

    def __init__(self, n_days: int = 32, n_tickers: int = 256,
                 seed: int = 0, missing_prob: float = 0.02,
                 session=None):
        from ..markets import get_session
        self.session = get_session(session)
        rng = np.random.default_rng(seed)
        shape = (n_days, n_tickers, self.session.n_slots)
        close = 10.0 * np.exp(np.cumsum(
            rng.standard_normal(shape, dtype=np.float32)
            * np.float32(1e-3), axis=-1))
        open_ = close * (1 + rng.standard_normal(shape, dtype=np.float32)
                         * np.float32(1e-4))
        high = np.maximum(open_, close) * 1.0002
        low = np.minimum(open_, close) * 0.9998
        volume = (rng.integers(0, 1000, shape) * 100).astype(np.float32)
        bars = np.stack([open_, high, low, close, volume], axis=-1)
        bars[..., :4] = np.round(bars[..., :4], 2)  # tick-aligned
        self._bars = bars.astype(np.float32)
        self._mask = rng.random(shape, dtype=np.float32) >= missing_prob
        self.codes: Tuple[str, ...] = tuple(
            f"{600000 + i:06d}" for i in range(n_tickers))
        d0 = np.datetime64("2024-01-02")
        self.days: Tuple[str, ...] = tuple(
            str(d0 + np.timedelta64(i, "D")) for i in range(n_days))

    @property
    def n_days(self) -> int:
        return len(self.days)

    @property
    def n_tickers(self) -> int:
        return len(self.codes)

    def slab(self, start: int, end: int):
        """``(bars [D, T, 240, 5], mask [D, T, 240])`` for days
        ``[start, end)`` — views, no copy."""
        return self._bars[start:end], self._mask[start:end]


class MinuteDirSource:
    """A directory of day-file parquets, gridded ONCE at construction
    onto a single union-code ticker axis (``pipeline._grid_batch``) so
    every day-range shares one ``[*, T, 240, *]`` layout — the property
    that lets blocks of equal day extent share one compiled executable.

    The whole directory's dense tensor lives in host RAM (a trading
    year of 5000 tickers is ~70 GB raw f32 — size the directory, or the
    source, to the host). A production deployment would page day groups
    from disk; this source is the correctness-first resident form.
    """

    #: day files carry cn_ashare wall-clock timestamps; the dir
    #: source grids on the canonical session
    session = None

    def __init__(self, minute_dir: str):
        from ..data import io as dio
        from ..pipeline import _grid_batch
        files = dio.list_day_files(minute_dir)
        if not files:
            raise ValueError(f"no day files under {minute_dir!r}")
        day_data = [(d, dio.read_minute_day_raw(p)) for d, p in files]
        bars, mask, codes, _present = _grid_batch(day_data)
        self._bars = bars.astype(np.float32)
        self._mask = mask
        self.codes = tuple(str(c) for c in codes)
        self.days = tuple(str(d) for d, _ in day_data)

    @property
    def n_days(self) -> int:
        return len(self.days)

    @property
    def n_tickers(self) -> int:
        return len(self.codes)

    def slab(self, start: int, end: int):
        return self._bars[start:end], self._mask[start:end]
