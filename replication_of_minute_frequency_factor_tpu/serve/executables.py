"""Keyed AOT executable cache — compile-once semantics for the service.

bench.py's ``_aot_resident`` memo proved the shape: lowering re-traces
the whole 58-kernel graph (seconds of host work), so a warm hit must
skip the ``.lower()`` call itself, not just the ``.compile()``. This
generalizes that memo into an injectable object the serving layer keys
on everything that shapes a module (buffer length, wire spec, factor
names, quirks, rolling backend, query-static params), with every build
routed through ``telemetry.attribution.compile_with_telemetry`` so the
``xla.compiles{fn=...}`` counter is the ground truth for "did this
request compile anything" — the serving acceptance gate reads it.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Hashable, Optional


class ExecutableCache:
    """Hashable-key -> compiled-executable map with compile-once
    semantics.

    ``get(label, key, lower_fn)`` returns the cached executable for
    ``key`` or builds it once: ``lower_fn()`` must return a
    ``jax.jit(...).lower(...)`` result, which is compiled through
    ``compile_with_telemetry(label, ...)``. Builds are serialized under
    one lock (the request loop is single-threaded; concurrent callers
    must not duplicate a seconds-scale compile), hits are lock-scoped
    dict reads. Counters: ``serve.executables{outcome=hit|miss}``;
    gauge: ``serve.executables_resident``.
    """

    def __init__(self, telemetry=None):
        self._lock = threading.Lock()
        self._exes: Dict[Hashable, object] = {}
        self._telemetry = telemetry

    def _tel(self):
        if self._telemetry is not None:
            return self._telemetry
        from ..telemetry import get_telemetry
        return get_telemetry()

    def __len__(self) -> int:
        with self._lock:
            return len(self._exes)

    def get(self, label: str, key: Hashable,
            lower_fn: Callable[[], object],
            compile_cost: Optional[dict] = None):
        """The compiled executable for ``key``; built once via
        ``compile_with_telemetry(label, lower_fn())``. ``compile_cost``
        (a mutable dict) receives the build's wall seconds under
        ``"compile_s"`` (accumulated — bench's phases contract)."""
        import time

        tel = self._tel()
        with self._lock:
            exe = self._exes.get(key)
            if exe is not None:
                tel.counter("serve.executables", outcome="hit")
                return exe
            # build under the lock: a second caller with the same key
            # must wait for one compile, not start its own
            from ..telemetry import attribution as _attr
            tel.counter("serve.executables", outcome="miss")
            t0 = time.perf_counter()
            exe = _attr.compile_with_telemetry(label, lower_fn(),
                                               telemetry=self._telemetry)
            if compile_cost is not None:
                compile_cost["compile_s"] = round(
                    compile_cost.get("compile_s", 0.0)
                    + time.perf_counter() - t0, 3)
            self._exes[key] = exe
            tel.gauge("serve.executables_resident", len(self._exes))
            return exe
