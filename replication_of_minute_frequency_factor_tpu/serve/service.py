"""The resident request loop: async batching, coalescing, load shedding.

``FactorServer`` is the process a notebook (or the HTTP binding) talks
to. Requests enqueue as futures; ONE worker thread drains the queue in
micro-batches (``batch_window_s`` collection window, ``max_batch``
bound), groups each batch by day-range, and answers every group from
ONE device block — concurrent queries over the same range therefore
coalesce into a single fused dispatch (or a single exposure-cache hit),
which is the scaling property the whole serving layer exists for.

Failure containment mirrors the batch pipeline's breaker: consecutive
failed dispatches open the circuit and subsequent submits are SHED
(fail fast with :class:`LoadShedError`) until a cooldown lapses; the
first request after the cooldown is the half-open probe. A full queue
sheds too — backpressure must reach the caller as an error, not as an
unbounded latency tail.

graftlint note (docs/static-analysis.md): this file is the declared
GL-A3 *boundary module* of the ``serve/`` layer — its one allowed host
sync is the ``np.asarray`` fetch that materializes a query's answer.
Everything device-side stays in :mod:`.engine`.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from .engine import ServeEngine
from .executables import ExecutableCache
from .expcache import DeviceExposureCache

_SENTINEL = None  # queue poison pill (requests are _Pending objects)

QUERY_KINDS = ("factors", "ic", "decile")


class LoadShedError(RuntimeError):
    """The server refused the request up front: breaker open after
    sustained dispatch failure, or the bounded queue is full. Callers
    retry later (or against another replica) — the error IS the
    backpressure signal."""


@dataclasses.dataclass(frozen=True)
class Query:
    """One question over a day-range ``[start, end)`` (indices into the
    source's day axis — the coalescing key is ``(start, end)``)."""
    kind: str                                  # factors | ic | decile
    start: int
    end: int
    names: Optional[Tuple[str, ...]] = None    # factors: subset (None=all)
    factor: Optional[str] = None               # ic / decile
    horizon: int = 1                           # forward-return horizon
    group_num: int = 5                         # decile buckets


@dataclasses.dataclass
class _Pending:
    query: Query
    future: Future
    t_enqueue: float


@dataclasses.dataclass
class ServeConfig:
    """Serving knobs (the compute knobs stay on ``config.Config``)."""
    #: micro-batch collection window after the first dequeued request
    batch_window_s: float = 0.002
    #: most requests drained into one micro-batch
    max_batch: int = 64
    #: bounded request queue; a full queue sheds (backpressure)
    queue_limit: int = 1024
    #: device-byte budget of the exposure cache (LRU past it)
    cache_bytes: int = 256 * 1024 * 1024
    #: consecutive failed dispatches before the breaker opens
    breaker_threshold: int = 3
    #: seconds the open breaker sheds before the half-open probe
    breaker_cooldown_s: float = 1.0


class FactorServer:
    """The long-lived factor service over one data source.

    ``start=False`` constructs the server with the worker paused —
    submitted requests queue up and are drained on :meth:`start` (the
    deterministic way to exercise coalescing in tests and smokes).
    """

    def __init__(self, source, names: Optional[Sequence[str]] = None,
                 serve_cfg: Optional[ServeConfig] = None,
                 replicate_quirks: bool = True,
                 rolling_impl: Optional[str] = None,
                 telemetry=None, start: bool = True):
        from ..models.registry import factor_names
        from ..telemetry import get_telemetry
        self.source = source
        self.names: Tuple[str, ...] = tuple(names) if names is not None \
            else factor_names()
        self.scfg = serve_cfg or ServeConfig()
        self.telemetry = telemetry if telemetry is not None \
            else get_telemetry()
        self.executables = ExecutableCache(telemetry=self.telemetry)
        self.engine = ServeEngine(self.names,
                                  replicate_quirks=replicate_quirks,
                                  rolling_impl=rolling_impl,
                                  telemetry=self.telemetry,
                                  executables=self.executables)
        self.cache = DeviceExposureCache(self.scfg.cache_bytes,
                                         telemetry=self.telemetry)
        self._q: "queue.Queue" = queue.Queue(maxsize=self.scfg.queue_limit)
        self._state_lock = threading.Lock()
        self._consecutive = 0
        self._open_until: Optional[float] = None
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # --- lifecycle ------------------------------------------------------
    def start(self) -> "FactorServer":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._worker,
                                            daemon=True,
                                            name="factor-serve-worker")
            self._thread.start()
        return self

    def close(self, timeout: float = 10.0) -> None:
        """Drain-and-stop: queued requests are still answered; new
        submits are refused."""
        self._closed = True
        if self._thread is not None and self._thread.is_alive():
            self._q.put(_SENTINEL)
            self._thread.join(timeout)

    def __enter__(self) -> "FactorServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- client side ----------------------------------------------------
    def client(self, timeout: Optional[float] = 60.0) -> "ServeClient":
        return ServeClient(self, timeout=timeout)

    def _validate(self, q: Query) -> None:
        if q.kind not in QUERY_KINDS:
            raise ValueError(f"unknown query kind {q.kind!r} "
                             f"(one of {QUERY_KINDS})")
        n_days = self.source.n_days
        if not (0 <= q.start < q.end <= n_days):
            raise ValueError(f"day range [{q.start}, {q.end}) outside "
                             f"the source's {n_days} days")
        if q.kind == "factors":
            unknown = [n for n in (q.names or ()) if n not in self.names]
            if unknown:
                raise ValueError(f"unknown factor(s) {unknown}; server "
                                 f"holds {len(self.names)}")
        else:
            if q.factor not in self.names:
                raise ValueError(f"unknown factor {q.factor!r}")
            if not (1 <= q.horizon < q.end - q.start):
                raise ValueError(
                    f"horizon {q.horizon} needs a range longer than "
                    f"itself (got {q.end - q.start} days)")
            if q.kind == "decile" and q.group_num < 2:
                raise ValueError("group_num must be >= 2")

    def submit(self, q: Query) -> Future:
        """Enqueue; returns a Future resolving to the answer dict.
        Raises :class:`LoadShedError` immediately when shedding (open
        breaker / full queue) and ``ValueError`` on a malformed query —
        validation cost stays on the caller's thread."""
        if self._closed:
            raise RuntimeError("server is closed")
        self._validate(q)
        tel = self.telemetry
        now = time.monotonic()
        with self._state_lock:
            if self._open_until is not None:
                if now < self._open_until:
                    tel.counter("serve.load_shed", reason="breaker")
                    raise LoadShedError(
                        "breaker open after "
                        f"{self._consecutive} consecutive dispatch "
                        "failures; retry after the cooldown")
                # half-open: this request is the probe; keep the gate up
                # for everyone else until it succeeds
                self._open_until = now + self.scfg.breaker_cooldown_s
        pending = _Pending(q, Future(), now)
        try:
            self._q.put_nowait(pending)
        except queue.Full:
            tel.counter("serve.load_shed", reason="queue_full")
            raise LoadShedError(
                f"request queue full ({self.scfg.queue_limit})") from None
        tel.counter("serve.requests", kind=q.kind)
        self._note_depth()
        return pending.future

    def _note_depth(self) -> None:
        depth = self._q.qsize()
        self.telemetry.gauge("serve.queue_depth", depth)
        self.telemetry.observe("serve.queue_depth", depth)

    # --- breaker --------------------------------------------------------
    def _breaker_failure(self) -> None:
        tel = self.telemetry
        with self._state_lock:
            self._consecutive += 1
            tel.gauge("serve.breaker_consecutive_failures",
                      self._consecutive)
            if self._consecutive >= self.scfg.breaker_threshold:
                self._open_until = (time.monotonic()
                                    + self.scfg.breaker_cooldown_s)
                tel.counter("serve.breaker_trips")

    def _breaker_ok(self) -> None:
        with self._state_lock:
            self._consecutive = 0
            self._open_until = None
        self.telemetry.gauge("serve.breaker_consecutive_failures", 0)

    # --- worker ---------------------------------------------------------
    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is _SENTINEL:
                return
            batch = [item]
            deadline = time.monotonic() + self.scfg.batch_window_s
            stop_after = False
            while len(batch) < self.scfg.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _SENTINEL:
                    stop_after = True
                    break
                batch.append(nxt)
            self._note_depth()
            self.telemetry.observe("serve.batch_size", len(batch))
            groups: Dict[Tuple[int, int], list] = {}
            for p in batch:
                groups.setdefault((p.query.start, p.query.end),
                                  []).append(p)
            self.telemetry.gauge("serve.inflight", len(batch))
            for key, group in groups.items():
                self._dispatch_group(key, group)
            self.telemetry.gauge("serve.inflight", 0)
            if stop_after:
                return

    def _dispatch_group(self, key: Tuple[int, int], group: list) -> None:
        """One device block answers every request in ``group`` — the
        coalescing contract. A block failure fails the whole group and
        bumps the breaker once."""
        tel = self.telemetry
        t_dispatch = time.monotonic()
        with tel.tracer("serve.dispatch"):
            try:
                t0 = time.perf_counter()
                block = self.cache.get(key)
                if block is None:
                    bars, mask = self.source.slab(*key)
                    block = self.engine.build_block(bars, mask)
                    self.cache.put(key, block)
                    tel.counter("serve.dispatches")
                tel.observe("serve.stage_seconds",
                            time.perf_counter() - t0, stage="block")
            except Exception as e:  # noqa: BLE001 — fail the group, shed
                for p in group:
                    p.future.set_exception(e)
                tel.counter("serve.failures", stage="block")
                self._breaker_failure()
                return
            if len(group) > 1:
                tel.counter("serve.coalesced_dispatches")
                tel.counter("serve.coalesced_requests", len(group))
            fetched: dict = {}
            ok = True
            for p in group:
                t0 = time.perf_counter()
                try:
                    result = self._answer(block, p.query, fetched)
                except Exception as e:  # noqa: BLE001 — per-request
                    p.future.set_exception(e)
                    tel.counter("serve.failures", stage="answer")
                    ok = False
                    continue
                p.future.set_result(result)
                now = time.monotonic()
                tel.observe("serve.stage_seconds",
                            time.perf_counter() - t0, stage="answer")
                tel.observe("serve.stage_seconds",
                            t_dispatch - p.t_enqueue, stage="queue_wait")
                tel.observe("serve.request_seconds", now - p.t_enqueue,
                            kind=p.query.kind)
        if ok:
            self._breaker_ok()
        else:
            self._breaker_failure()

    # --- answers (the boundary: device block -> host JSON-able) ---------
    def _days_codes(self, q: Query) -> dict:
        return {"days": list(self.source.days[q.start:q.end]),
                "start": q.start, "end": q.end}

    def _host_exposures(self, block, fetched: dict) -> np.ndarray:
        """The group's ONE host fetch of the stacked exposures (memoised
        across the group's factors-queries) — the declared GL-A3
        boundary sync of the request loop."""
        if "exposures" not in fetched:
            fetched["exposures"] = np.asarray(block["exposures"])
        return fetched["exposures"]

    def _answer(self, block, q: Query, fetched: dict) -> dict:
        out = self._days_codes(q)
        if q.kind == "factors":
            exp = self._host_exposures(block, fetched)
            names = q.names or self.names
            out["codes"] = list(self.source.codes)
            out["exposures"] = {
                n: exp[self.names.index(n)].tolist() for n in names}
            return out
        if q.kind == "ic":
            ic, rank_ic = self.engine.ic(block, q.factor, q.horizon)
            ic = np.asarray(ic)
            rank_ic = np.asarray(rank_ic)
            out.update({
                "factor": q.factor, "horizon": q.horizon,
                "ic": ic.tolist(), "rank_ic": rank_ic.tolist(),
                "mean_ic": _finite_mean(ic),
                "mean_rank_ic": _finite_mean(rank_ic)})
            return out
        _labels, counts, mean_ret = self.engine.decile(
            block, q.factor, q.horizon, q.group_num)
        out.update({
            "factor": q.factor, "horizon": q.horizon,
            "group_num": q.group_num,
            "counts": np.asarray(counts).tolist(),
            "mean_fwd_ret": np.asarray(mean_ret).tolist()})
        return out


def _finite_mean(x: np.ndarray):
    f = x[np.isfinite(x)]
    return round(f.mean().tolist(), 8) if f.size else None


class ServeClient:
    """In-process client API — the notebook-facing surface. Each method
    submits one :class:`Query` and blocks on its future."""

    def __init__(self, server: FactorServer,
                 timeout: Optional[float] = 60.0):
        self._server = server
        self._timeout = timeout

    def factors(self, start: int, end: int,
                names: Optional[Sequence[str]] = None) -> dict:
        q = Query("factors", start, end,
                  names=tuple(names) if names else None)
        return self._server.submit(q).result(self._timeout)

    def ic(self, factor: str, start: int, end: int,
           horizon: int = 1) -> dict:
        q = Query("ic", start, end, factor=factor, horizon=horizon)
        return self._server.submit(q).result(self._timeout)

    def decile(self, factor: str, start: int, end: int,
               horizon: int = 1, group_num: int = 5) -> dict:
        q = Query("decile", start, end, factor=factor, horizon=horizon,
                  group_num=group_num)
        return self._server.submit(q).result(self._timeout)
