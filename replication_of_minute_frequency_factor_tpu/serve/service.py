"""The resident request loop: async batching, coalescing, load shedding.

``FactorServer`` is the process a notebook (or the HTTP binding) talks
to. Requests enqueue as futures; ONE worker thread drains the queue in
micro-batches (``batch_window_s`` collection window, ``max_batch``
bound), groups each batch by day-range, and answers every group from
ONE device block — concurrent queries over the same range therefore
coalesce into a single fused dispatch (or a single exposure-cache hit),
which is the scaling property the whole serving layer exists for.

Streaming (ISSUE 7): a server constructed with ``stream=True`` also
owns a :class:`..stream.engine.StreamEngine` over the source's ticker
universe and accepts two more request shapes through the SAME queue —
:meth:`FactorServer.ingest` (minute bars advancing the device-resident
carry) and ``Query(kind="intraday")`` (the carry's partial-day
exposures + readiness plane). Within one micro-batch every ingest
applies in arrival order BEFORE any intraday query (latest-view
semantics), and concurrent intraday queries coalesce onto ONE snapshot
dispatch exactly like same-range block queries do.

Failure containment mirrors the batch pipeline's breaker: consecutive
failed dispatches open the circuit and subsequent submits are SHED
(fail fast with :class:`LoadShedError`) until a cooldown lapses; the
first request after the cooldown is the half-open probe. A full queue
sheds too — backpressure must reach the caller as an error, not as an
unbounded latency tail.

graftlint note (docs/static-analysis.md): this file is the declared
GL-A3 *boundary module* of the ``serve/`` layer — its one allowed host
sync is the ``np.asarray`` fetch that materializes a query's answer.
Everything device-side stays in :mod:`.engine`.
"""

from __future__ import annotations

import contextlib
import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..telemetry.opsplane import FlightRecorder, canonical_trace_id
from .engine import ServeEngine
from .executables import ExecutableCache
from .expcache import DeviceExposureCache

_SENTINEL = None  # queue poison pill (requests are _Pending objects)

QUERY_KINDS = ("factors", "ic", "decile", "intraday")


class LoadShedError(RuntimeError):
    """The server refused the request up front: breaker open after
    sustained dispatch failure, or the bounded queue is full. Callers
    retry later (or against another replica) — the error IS the
    backpressure signal.

    ``retry_after_s`` (ISSUE 11) is the server's backoff hint: the
    remaining breaker cooldown on a breaker shed, the full cooldown on
    a full-queue shed (the queue has no clock; the breaker cooldown is
    the service's one declared backoff constant). The HTTP binding
    renders it as a ``Retry-After`` header on every 503."""

    def __init__(self, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


@dataclasses.dataclass(frozen=True)
class Query:
    """One question over a day-range ``[start, end)`` (indices into the
    source's day axis — the coalescing key is ``(start, end)``). The
    ``intraday`` kind (ISSUE 7) instead reads the live streaming
    carry's partial-day exposures; its range is ignored (use 0, 0)."""
    kind: str                         # factors | ic | decile | intraday
    start: int = 0
    end: int = 0
    names: Optional[Tuple[str, ...]] = None    # factors: subset (None=all)
    factor: Optional[str] = None               # ic / decile
    horizon: int = 1                           # forward-return horizon
    group_num: int = 5                         # decile buckets
    #: answer encoding (ISSUE 20): ``json`` answers are host dicts;
    #: ``wire`` ships the block's packed result-wire payload verbatim
    #: (``factors`` kind over the FULL factor set only — the payload IS
    #: the whole [F, D, T] block; see docs/serving.md "The binary
    #: edge"). Not part of the coalescing key: a wire and a json query
    #: over the same range share one dispatch group.
    encoding: str = "json"


@dataclasses.dataclass(frozen=True)
class Discover:
    """One bounded-generations factor-discovery job (ISSUE 14): an
    evolutionary search over the source's days ``[start, end)``
    through the SAME request queue as every other request —
    breaker/shed/trace-ID semantics unchanged. The worker runs the
    search (``research/evolve.DiscoveryEngine``, warm executables,
    one labeled host sync per generation), registers the best genome
    as a live factor name (``disc_<hash>``), persists its genome
    record when ``ServeConfig.research_dir`` is set, and resolves the
    future with the name + backtest stats. Generations/population are
    bounded by ``ServeConfig.discover_max_*`` at validation."""
    start: int
    end: int
    generations: int = 4
    pop: int = 128
    seed: int = 0
    horizon: int = 1
    skeleton: str = "default"


@dataclasses.dataclass(frozen=True)
class Ingest:
    """Minute bars for the streaming carry (ISSUE 7): ``bars
    [B, T, 5]`` f32 / ``present [B, T]`` bool host arrays advance the
    resident day by ``B`` minutes. Within a micro-batch every ingest
    applies IN ARRIVAL ORDER and BEFORE any intraday query —
    latest-view semantics."""
    bars: object
    present: object


@dataclasses.dataclass
class _Pending:
    query: Query
    future: Future
    t_enqueue: float
    #: request-scoped trace ID (ISSUE 8): generated at admission or
    #: propagated from the caller (``X-Trace-Id`` / ``trace_id=``)
    trace_id: str = ""
    #: admission timestamp on the perf_counter clock — the span
    #: tracer's timebase, for explicit lifecycle span events
    t_pc: float = 0.0


@dataclasses.dataclass
class ServeConfig:
    """Serving knobs (the compute knobs stay on ``config.Config``)."""
    #: micro-batch collection window after the first dequeued request
    batch_window_s: float = 0.002
    #: most requests drained into one micro-batch
    max_batch: int = 64
    #: bounded request queue; a full queue sheds (backpressure)
    queue_limit: int = 1024
    #: device-byte budget of the exposure cache (LRU past it)
    cache_bytes: int = 256 * 1024 * 1024
    #: consecutive failed dispatches before the breaker opens
    breaker_threshold: int = 3
    #: seconds the open breaker sheds before the half-open probe
    breaker_cooldown_s: float = 1.0
    #: flight-recorder ring bound (recent request traces; ISSUE 8)
    flight_ring: int = 256
    #: where anomaly dumps land (None = ring-only, no files written)
    flight_dir: Optional[str] = None
    #: HBM watermark sampler thread period (0 disables the thread;
    #: dispatch-boundary sampling stays on either way)
    hbm_sample_period_s: float = 0.5
    #: timeline sampler thread period (ISSUE 16; 0 disables the
    #: thread — the SLO plane then evaluates only on explicit
    #: ``timeline.sample()`` calls). Host-side registry reads only;
    #: never a device sync.
    timeline_sample_period_s: float = 0.5
    #: divides every SLO burn window (telemetry/slo.BURN_WINDOWS):
    #: 1.0 = the production SRE 5m/1h + 6h/3d pairs; tests/smokes set
    #: thousands to compress hours into test seconds
    slo_time_scale: float = 1.0
    #: default latency objective: p99 of serve.request_seconds must
    #: stay under this many ms
    slo_latency_ms: float = 250.0
    #: default freshness objective (streaming servers): seconds since
    #: the last applied ingest must stay under this
    slo_staleness_s: float = 120.0
    #: ship factors/intraday answers through the blocked-quantized
    #: result wire (ISSUE 10): the block's exposures encode on device
    #: (one warm dispatch from the cached RAW f32 block — never from a
    #: decode, so the exposure cache can't double-quantize) and the
    #: answer IS the host-side dequantize of the fetched payload.
    #: Opt-in: quantized slices carry the pinned range-relative error
    #: (data/result_wire.RESULT_BOUNDS), which answer consumers must
    #: accept; widened slices stay bitwise.
    result_wire: bool = False
    #: where discovered-genome records persist as ``disc_<hash>.json``
    #: (ISSUE 14; None = in-memory registration only). Set it beside
    #: the telemetry bundle so a discovery's provenance ships with the
    #: run's evidence.
    research_dir: Optional[str] = None
    #: upper bounds a ``POST /v1/discover`` request is validated
    #: against — a research server stays a bounded-latency service,
    #: not an unbounded compute endpoint
    discover_max_generations: int = 64
    discover_max_pop: int = 8192
    #: shard discovery populations over this server's visible devices
    #: (``parallel.resident_mesh``; ISSUE 14). Applied only when more
    #: than one device is visible — otherwise the engine runs
    #: single-device, silently (the ``discover.n_shards`` gauge says
    #: which ran), mirroring ``stream_sharded``.
    discover_sharded: bool = False
    #: place the streaming carry over a tickers mesh spanning this
    #: server's devices (ISSUE 13): cohort ingest and snapshot stop
    #: being single-device-bound — every carry leaf gets a
    #: ``NamedSharding`` over the replica submesh's ticker axis, with
    #: snapshot/finalize bitwise the unsharded engine's (the
    #: tests/test_stream.py re-placement pin). Applied only when more
    #: than one device is visible AND the universe divides over them;
    #: otherwise the engine stays single-device, silently — the
    #: ``stream.carry_sharded`` gauge says which one runs.
    stream_sharded: bool = False
    #: front-door transport the CLI binds (ISSUE 20): ``edge`` is the
    #: evented selectors loop (:mod:`.edge` — keep-alive, pipelining,
    #: binary wire answers, per-tenant quotas); ``legacy`` keeps the
    #: stdlib thread-per-connection server for A/B and fallback. Code
    #: that calls :func:`.http.serve_http` / :func:`.edge.serve_edge`
    #: directly picks its own transport regardless of this knob.
    edge: str = "edge"
    #: per-tenant admission quota at the EDGE (ISSUE 20): sustained
    #: requests/second each ``X-Tenant`` (or API key) may submit,
    #: token-bucket enforced ABOVE pod admission; 0 disables. Refused
    #: requests get 429 + ``Retry-After``, mirroring the shed contract.
    tenant_quota_rps: float = 0.0
    #: token-bucket burst depth (0 -> max(1, tenant_quota_rps))
    tenant_quota_burst: float = 0.0
    #: seconds an edge connection may sit idle (including mid-request —
    #: the slow-loris bound) before the loop reaps it
    edge_idle_timeout_s: float = 30.0
    #: streaming snapshot finalize implementation for this server's
    #: StreamEngine (ISSUE 18): None adopts ``Config.finalize_impl``
    #: (default 'exact', the bitwise batch-prefix graph); 'fast'
    #: materializes the foldable kernel subset from carried sufficient
    #: statistics in O(F·T) per snapshot (docs/streaming.md "Exactness
    #: classes"). The engine's RESOLVED choice — 'fast' degrades to
    #: 'exact' when the served name set has no foldable kernel — is
    #: reported in ``/healthz`` as ``stream_finalize_impl``.
    stream_finalize_impl: Optional[str] = None


#: graftlint Tier C concurrency contract (analysis/concurrency_tier.py;
#: runtime twin telemetry/lockcheck.py): the breaker state and the
#: drain flag are shared between caller threads (submit/ingest/
#: discover) and the worker; ``_state_lock`` guards all of them.
#: ``_dispatch_seq`` (worker-thread-only) and ``names`` (documented
#: atomic-tuple-swap, worker-writes/callers-read) stay out by design.
GLC_CONTRACT = {
    "FactorServer": {
        "lock": "_state_lock",
        "guards": ("_consecutive", "_open_until", "_closed"),
        "init": (),
        "locked": (),
    },
}


class FactorServer:
    """The long-lived factor service over one data source.

    ``start=False`` constructs the server with the worker paused —
    submitted requests queue up and are drained on :meth:`start` (the
    deterministic way to exercise coalescing in tests and smokes).
    """

    def __init__(self, source, names: Optional[Sequence[str]] = None,
                 serve_cfg: Optional[ServeConfig] = None,
                 replicate_quirks: bool = True,
                 rolling_impl: Optional[str] = None,
                 telemetry=None, start: bool = True,
                 stream: bool = False,
                 stream_batches: Sequence[int] = (1,),
                 replica_label: Optional[str] = None,
                 devices: Optional[Sequence] = None,
                 research: bool = False):
        from ..models.registry import factor_names
        from ..telemetry import get_telemetry
        self.source = source
        self.names: Tuple[str, ...] = tuple(names) if names is not None \
            else factor_names()
        self.scfg = serve_cfg or ServeConfig()
        self.telemetry = telemetry if telemetry is not None \
            else get_telemetry()
        #: replica identity (ISSUE 11): the fleet spawns N servers over
        #: disjoint device submeshes; ``replica_label`` names this one
        #: in health payloads / flight dumps and ``devices`` pins every
        #: device dispatch (construction warmup AND the worker loop run
        #: under ``jax.default_device(devices[0])``, so blocks, carries
        #: and executables live on this replica's submesh only). A
        #: standalone server keeps both unset and reports the process's
        #: full device view.
        self.replica_label = replica_label or "standalone"
        self.devices: Optional[tuple] = (tuple(devices) if devices
                                         else None)
        #: market session (ISSUE 15): adopted from the source (a
        #: source built for us_390 serves us_390 — the session is a
        #: property of the DATA, not a request knob); sources without
        #: the attribute serve the canonical cn_ashare_240 day
        from ..markets import get_session
        self.session = get_session(getattr(source, "session", None))
        self.executables = ExecutableCache(telemetry=self.telemetry)
        with self._device_ctx():
            self.engine = ServeEngine(self.names,
                                      replicate_quirks=replicate_quirks,
                                      rolling_impl=rolling_impl,
                                      telemetry=self.telemetry,
                                      executables=self.executables,
                                      session=self.session)
            self.cache = DeviceExposureCache(self.scfg.cache_bytes,
                                             telemetry=self.telemetry)
            #: ISSUE 7: the live intraday engine over the source's
            #: ticker universe, sharing THE executable cache (one
            #: compile-count ground truth). Warmed at construction for
            #: the declared ingest micro-batch shapes, so steady-state
            #: ingest/intraday traffic compiles nothing.
            self.stream_engine = None
            if stream:
                import jax as _jax

                from ..stream.engine import StreamEngine
                stream_mesh = None
                if self.scfg.stream_sharded:
                    from ..parallel.mesh import resident_mesh
                    devs = (list(self.devices) if self.devices
                            else list(_jax.devices()))
                    if (len(devs) > 1
                            and source.n_tickers % len(devs) == 0):
                        stream_mesh = resident_mesh(len(devs), devs)
                self.telemetry.gauge(
                    "stream.carry_sharded",
                    0 if stream_mesh is None
                    else stream_mesh.devices.size)
                self.stream_engine = StreamEngine(
                    source.n_tickers, names=self.names,
                    replicate_quirks=replicate_quirks,
                    rolling_impl=rolling_impl, telemetry=self.telemetry,
                    executables=self.executables, mesh=stream_mesh,
                    session=self.session,
                    finalize_impl=self.scfg.stream_finalize_impl)
                self.stream_engine.warmup(micro_batches=stream_batches)
            #: ISSUE 14: the factor-discovery engine, sharing THE
            #: executable cache (a server's discovery jobs and its
            #: query graphs live under one compile-count ground
            #: truth). Built-in names are pinned at construction so
            #: ``factor_list`` can split built-in from discovered
            #: after registrations grow ``self.names``.
            self.research_engine = None
            if research:
                import jax as _jax

                from ..research.evolve import DiscoveryEngine
                research_mesh = None
                if self.scfg.discover_sharded:
                    from ..parallel.mesh import resident_mesh
                    devs = (list(self.devices) if self.devices
                            else list(_jax.devices()))
                    if len(devs) > 1:
                        research_mesh = resident_mesh(len(devs), devs)
                self.research_engine = DiscoveryEngine(
                    telemetry=self.telemetry,
                    executables=self.executables, mesh=research_mesh)
        self._builtin_names: Tuple[str, ...] = self.names
        #: PR 14 residue (ISSUE 15 satellite): a research server's
        #: discoveries survive the process — restart reloads every
        #: persisted ``disc_<hash>.json`` under ``research_dir`` back
        #: into the live registry and this server's factor set, so a
        #: previously discovered name is queryable the moment the
        #: server is up (round-trip gated in tests/test_serve.py)
        if research and self.scfg.research_dir:
            self._reload_discoveries()
        self._q: "queue.Queue" = queue.Queue(maxsize=self.scfg.queue_limit)
        self._state_lock = threading.Lock()
        self._consecutive = 0
        self._open_until: Optional[float] = None
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        #: ops plane (ISSUE 8): flight recorder for anomaly capture +
        #: the telemetry-bound HBM watermark sampler
        self.flight = FlightRecorder(telemetry=self.telemetry,
                                     ring=self.scfg.flight_ring,
                                     dump_dir=self.scfg.flight_dir)
        #: factor-health plane (ISSUE 12): drift bursts dump through
        #: THIS server's flight recorder into the same flight_dir, so
        #: a factor_drift_burst capture sits next to the breaker-trip
        #: ones and carries the recent request ring
        self.telemetry.factorplane.configure(
            dump_dir=self.scfg.flight_dir, flight=self.flight)
        self._t_start = time.monotonic()
        self._dispatch_seq = 0  # worker-thread-only; no lock needed
        if self.scfg.hbm_sample_period_s > 0:
            self.telemetry.hbm.start(self.scfg.hbm_sample_period_s)
        #: SLO plane (ISSUE 16): the continuous timeline sampler +
        #: declarative burn-rate objectives. The sampler reads only
        #: host-side state (registry snapshots, the stream engine's
        #: staleness mirror, the discovery engine's progress mirror);
        #: an alert transition force-dumps THIS server's flight
        #: recorder under the ``slo_burn`` trigger.
        self.timeline = self.telemetry.timeline
        self.sloplane = self.telemetry.sloplane
        if self.stream_engine is not None:
            eng = self.stream_engine

            def _stream_freshness(eng=eng):
                s = eng.staleness_s()
                if s is None:
                    return {}
                return {"stream.staleness_s": round(s, 6)}

            self.timeline.add_source(_stream_freshness)
        if self.research_engine is not None:
            self.timeline.add_source(self.research_engine.progress)
        from ..telemetry.slo import serve_objectives
        self.sloplane.configure(
            serve_objectives(latency_ms=self.scfg.slo_latency_ms,
                             staleness_s=self.scfg.slo_staleness_s,
                             streaming=self.stream_engine is not None),
            flight=self.flight, timeline=self.timeline,
            time_scale=self.scfg.slo_time_scale)
        if self.scfg.timeline_sample_period_s > 0:
            self.timeline.start(self.scfg.timeline_sample_period_s)
        from ..telemetry.lockcheck import maybe_install
        maybe_install(self)
        if start:
            self.start()

    def _device_ctx(self):
        """Pin device placement to this replica's submesh lead: every
        un-annotated ``device_put``/jit dispatch inside lands on
        ``devices[0]`` (thread-scoped, so N replicas in one process
        stay disjoint). A no-op for a standalone server."""
        if not self.devices:
            return contextlib.nullcontext()
        import jax
        return jax.default_device(self.devices[0])

    # --- lifecycle ------------------------------------------------------
    def start(self) -> "FactorServer":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._worker,
                                            daemon=True,
                                            name="factor-serve-worker")
            self._thread.start()
        return self

    def close(self, timeout: float = 10.0) -> None:
        """Drain-and-stop: queued requests are still answered; new
        submits are refused."""
        with self._state_lock:
            # GL-C1 bring-up finding: the flag is read by every
            # submit/ingest/discover caller; the unlocked write
            # worked only by CPython-coincidence
            self._closed = True
        if self._thread is not None and self._thread.is_alive():
            self._q.put(_SENTINEL)
            self._thread.join(timeout)
        if self.scfg.hbm_sample_period_s > 0:
            self.telemetry.hbm.stop()
        if self.scfg.timeline_sample_period_s > 0:
            self.timeline.stop()

    def debug_dump(self, out_dir: Optional[str] = None) -> Optional[str]:
        """On-demand flight-recorder capture (``POST /v1/debug/dump``):
        dump the ring + last-dispatch metadata + counter deltas now.
        Returns the dump path (None when no directory is configured)."""
        return self.flight.dump("manual", out_dir=out_dir, force=True)

    def __enter__(self) -> "FactorServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- client side ----------------------------------------------------
    def client(self, timeout: Optional[float] = 60.0) -> "ServeClient":
        return ServeClient(self, timeout=timeout)

    def _validate(self, q: Query) -> None:
        if q.kind not in QUERY_KINDS:
            raise ValueError(f"unknown query kind {q.kind!r} "
                             f"(one of {QUERY_KINDS})")
        if q.encoding not in ("json", "wire"):
            raise ValueError(f"unknown answer encoding {q.encoding!r} "
                             f"(json or wire)")
        if q.encoding == "wire" and (q.kind != "factors" or q.names):
            # the wire payload IS the whole [F, D, T] block — a subset
            # or a scalar-shaped answer has no packed representation
            raise ValueError(
                "wire encoding answers kind='factors' over the full "
                "factor set only (names=None); ask for json otherwise")
        if q.kind == "intraday":
            if self.stream_engine is None:
                raise ValueError("intraday queries need a server "
                                 "constructed with stream=True")
            # validate against the STREAM engine's factor set: a
            # discovered factor (ISSUE 14) grows self.names for block
            # queries, but the streaming carry's warm executables
            # were compiled over the construction-time set — genome
            # factors have no incremental-finalize class yet
            # (ROADMAP residue), so intraday must refuse them loudly
            unknown = [n for n in (q.names or ())
                       if n not in self.stream_engine.names]
            if unknown:
                raise ValueError(
                    f"unknown factor(s) {unknown} for intraday — "
                    f"non-streamable (a discovered factor) or "
                    f"unregistered; the stream engine holds "
                    f"{len(self.stream_engine.names)}")
            return
        n_days = self.source.n_days
        if not (0 <= q.start < q.end <= n_days):
            raise ValueError(f"day range [{q.start}, {q.end}) outside "
                             f"the source's {n_days} days")
        if q.kind == "factors":
            unknown = [n for n in (q.names or ()) if n not in self.names]
            if unknown:
                raise ValueError(f"unknown factor(s) {unknown}; server "
                                 f"holds {len(self.names)}")
        else:
            if q.factor not in self.names:
                raise ValueError(f"unknown factor {q.factor!r}")
            if not (1 <= q.horizon < q.end - q.start):
                raise ValueError(
                    f"horizon {q.horizon} needs a range longer than "
                    f"itself (got {q.end - q.start} days)")
            if q.kind == "decile" and q.group_num < 2:
                raise ValueError("group_num must be >= 2")

    def submit(self, q: Query,
               trace_id: Optional[str] = None) -> Future:
        """Enqueue; returns a Future resolving to the answer dict.
        Raises :class:`LoadShedError` immediately when shedding (open
        breaker / full queue) and ``ValueError`` on a malformed query —
        validation cost stays on the caller's thread. ``trace_id``
        propagates a caller-assigned request trace ID (ISSUE 8); None
        generates one at admission. The answer dict carries it back."""
        if self._closed:
            raise RuntimeError("server is closed")
        self._validate(q)
        return self._enqueue(q, q.kind, trace_id)

    def ingest(self, bars, present,
               trace_id: Optional[str] = None) -> Future:
        """Enqueue minute bars for the streaming carry: ``bars
        [B, T, 5]`` f32 / ``present [B, T]`` bool advance the resident
        day by ``B`` minutes through the request queue (so ordering
        against intraday queries is the worker's, not the caller's).
        Returns a Future resolving to ``{"minute", "bars"}``; sheds and
        validates exactly like :meth:`submit`."""
        if self._closed:
            raise RuntimeError("server is closed")
        if self.stream_engine is None:
            raise ValueError("ingest needs a server constructed with "
                             "stream=True")
        bars = np.ascontiguousarray(bars, np.float32)
        present = np.ascontiguousarray(present, bool)
        if bars.ndim != 3 or bars.shape[-1] != 5 \
                or present.shape != bars.shape[:2]:
            raise ValueError(
                f"ingest wants bars [B, T, 5] with present [B, T]; got "
                f"{bars.shape} / {present.shape}")
        if present.shape[1] != self.stream_engine.n_tickers:
            raise ValueError(
                f"got {present.shape[1]} tickers; the stream engine "
                f"holds {self.stream_engine.n_tickers}")
        return self._enqueue(Ingest(bars, present), "ingest", trace_id)

    def discover(self, start: int, end: int, generations: int = 4,
                 pop: int = 128, seed: int = 0, horizon: int = 1,
                 skeleton: str = "default",
                 trace_id: Optional[str] = None) -> Future:
        """Enqueue a bounded-generations discovery job over days
        ``[start, end)`` (ISSUE 14). Returns a Future resolving to
        the discovery answer (name, backtest stats, record path);
        sheds and validates exactly like :meth:`submit` — the breaker
        and the bounded queue apply to research traffic unchanged."""
        from ..research.evolve import resolve_skeleton
        if self._closed:
            raise RuntimeError("server is closed")
        if self.research_engine is None:
            raise ValueError("discover needs a server constructed "
                             "with research=True")
        n_days = self.source.n_days
        if not (0 <= start < end <= n_days):
            raise ValueError(f"day range [{start}, {end}) outside the "
                             f"source's {n_days} days")
        if not (1 <= horizon < end - start):
            raise ValueError(
                f"horizon {horizon} needs a range longer than itself "
                f"(got {end - start} days)")
        if not (1 <= generations
                <= self.scfg.discover_max_generations):
            raise ValueError(
                f"generations must be in [1, "
                f"{self.scfg.discover_max_generations}]")
        if not (2 <= pop <= self.scfg.discover_max_pop):
            raise ValueError(
                f"pop must be in [2, {self.scfg.discover_max_pop}]")
        resolve_skeleton(skeleton)  # raises on an unknown name
        return self._enqueue(
            Discover(int(start), int(end), int(generations), int(pop),
                     int(seed), int(horizon), skeleton),
            "discover", trace_id)

    def factor_list(self) -> dict:
        """``GET /v1/factors``: the server's live factor universe —
        the built-in names it was constructed over plus every factor
        discovered since, each immediately queryable by name through
        the normal ``/v1/query`` leg."""
        names = self.names  # one atomic read (registration swaps it)
        builtin = [n for n in names if n in self._builtin_names]
        discovered = [n for n in names if n not in self._builtin_names]
        return {"builtin": builtin, "discovered": discovered,
                "count": len(names),
                "research": self.research_engine is not None}

    def _enqueue(self, item, kind: str,
                 trace_id: Optional[str] = None) -> Future:
        """Shed gate + enqueue shared by queries and ingests. Every
        admitted request gets its trace ID HERE (propagated when the
        caller supplied a well-formed one, generated otherwise) — the
        single admission point, so no request can cross the queue
        anonymously."""
        tel = self.telemetry
        now = time.monotonic()
        with self._state_lock:
            if self._open_until is not None:
                if now < self._open_until:
                    tel.counter("serve.load_shed", reason="breaker")
                    self.flight.note_shed("breaker")
                    raise LoadShedError(
                        "breaker open after "
                        f"{self._consecutive} consecutive dispatch "
                        "failures; retry after the cooldown",
                        retry_after_s=self._open_until - now)
                # half-open: this request is the probe; keep the gate up
                # for everyone else until it succeeds
                self._open_until = now + self.scfg.breaker_cooldown_s
        pending = _Pending(item, Future(), now,
                           trace_id=canonical_trace_id(trace_id),
                           t_pc=time.perf_counter())
        try:
            self._q.put_nowait(pending)
        except queue.Full:
            tel.counter("serve.load_shed", reason="queue_full")
            self.flight.note_shed("queue_full")
            raise LoadShedError(
                f"request queue full ({self.scfg.queue_limit})",
                retry_after_s=self.scfg.breaker_cooldown_s) from None
        tel.counter("serve.requests", kind=kind)
        self._note_depth()
        return pending.future

    def _note_depth(self) -> None:
        depth = self._q.qsize()
        self.telemetry.gauge("serve.queue_depth", depth)
        self.telemetry.observe("serve.queue_depth", depth)

    # --- breaker --------------------------------------------------------
    def _breaker_failure(self) -> None:
        tel = self.telemetry
        tripped = False
        with self._state_lock:
            self._consecutive += 1
            tel.gauge("serve.breaker_consecutive_failures",
                      self._consecutive)
            if self._consecutive >= self.scfg.breaker_threshold:
                self._open_until = (time.monotonic()
                                    + self.scfg.breaker_cooldown_s)
                tel.counter("serve.breaker_trips")
                tripped = True
        if tripped:
            # flight-recorder anomaly capture (ISSUE 8): the ring holds
            # the failed requests' traces at this moment — dump outside
            # the state lock, forced (trips are rare by construction)
            self.flight.dump("breaker_trip", force=True)

    def _breaker_ok(self) -> None:
        with self._state_lock:
            self._consecutive = 0
            self._open_until = None
        self.telemetry.gauge("serve.breaker_consecutive_failures", 0)

    def breaker_state(self) -> str:
        """``closed`` / ``open`` / ``half_open`` — the breaker as a
        label (health payloads, the fleet routing policy). ``open``
        means submits shed right now; ``half_open`` means the cooldown
        lapsed and the next submit is the probe."""
        with self._state_lock:
            if self._open_until is None:
                return "closed"
            return ("open" if time.monotonic() < self._open_until
                    else "half_open")

    # --- health (ISSUE 11: one shape for standalone AND fleet) ----------
    def health(self) -> dict:
        """The ``/healthz`` payload: liveness + breaker + queue depth +
        flight/HBM markers, PLUS the ``replica`` identity block (label,
        device set, breaker state) — the standalone server and every
        fleet replica report the same shape, so the pod rollup is a
        dict of these with nothing translated."""
        with self._state_lock:
            open_until = self._open_until
            consecutive = self._consecutive
        hbm = self.telemetry.hbm.sample("healthz")
        if self.devices is not None:
            device_names = [str(d) for d in self.devices]
        else:
            import jax
            device_names = [str(d) for d in jax.devices()]
        payload = {
            "ok": True, "factors": len(self.names),
            "days": self.source.n_days,
            "session": self.session.name,
            "breaker_open": open_until is not None,
            "breaker_consecutive_failures": consecutive,
            "uptime_s": round(time.monotonic() - self._t_start, 3),
            "queue_depth": self._q.qsize(),
            "flight": {"requests": len(self.flight),
                       "dumps": self.flight.dump_count,
                       # ISSUE 16 satellite: non-forced dumps the 1/s
                       # rate limit dropped — no longer silent
                       "suppressed": self.flight.suppressed_count},
            "hbm_available": bool(hbm.get("available")),
            "research": self.research_engine is not None,
            "replica": {"label": self.replica_label,
                        "devices": device_names,
                        "breaker": self.breaker_state()},
            # factor-health block (ISSUE 12): the data-quality view —
            # worst-coverage factor, widen rate, drift bursts — shared
            # VERBATIM by the standalone endpoint and every fleet
            # replica (the pod rollup reads these, nothing translated),
            # like the replica identity block above
            "factor_health": self.telemetry.factorplane.summary(),
        }
        if self.stream_engine is not None:
            payload["stream_minute"] = self.stream_engine.minutes
            # ISSUE 16 satellite: wall-clock freshness next to the
            # cursor — shared VERBATIM standalone/replica (the fleet
            # pod rollup reads this key), None until the first ingest
            s = self.stream_engine.staleness_s()
            payload["stream_staleness_s"] = (None if s is None
                                             else round(s, 3))
            # ISSUE 18: the RESOLVED finalize impl — 'fast' only when
            # requested AND the served set has a foldable kernel, so
            # an operator reads what actually runs, not what was asked
            payload["stream_finalize_impl"] = \
                self.stream_engine.finalize_impl_resolved
        return payload

    # --- request-lifecycle recording (ISSUE 8) --------------------------
    def _complete(self, p: _Pending, op: str, status: str,
                  dispatch_id: int, group_size: int, block_s: float,
                  answer_s: float, t_dispatch: float,
                  error: Optional[BaseException] = None) -> None:
        """Close out one request's trace: emit the schema-v2 lifecycle
        record (admission → queue-wait → dispatch → answer), fan the
        coalesced dispatch's device time back to this member's trace ID
        as explicit span events, and feed the flight-recorder ring."""
        tel = self.telemetry
        now = time.monotonic()
        queue_wait = max(0.0, t_dispatch - p.t_enqueue)
        total = now - p.t_enqueue
        share = block_s / group_size if group_size else block_s
        data = {
            "queue_wait_s": round(queue_wait, 6),
            "dispatch_id": dispatch_id,
            "group_size": group_size,
            "coalesced": group_size > 1,
            "block_s": round(block_s, 6),
            "device_share_s": round(share, 6),
            "answer_s": round(answer_s, 6),
            "total_s": round(total, 6),
        }
        if error is not None:
            data["error"] = f"{type(error).__name__}: {error}"
        trace = {"trace_id": p.trace_id, "op": op, "status": status,
                 "data": data}
        tel.request(trace)
        self.flight.record_request(trace)
        tr = tel.tracer
        tr.add_span("serve.queue_wait", p.t_pc, queue_wait,
                    trace_id=p.trace_id)
        tr.add_span("serve.dispatch_share", p.t_pc + queue_wait, share,
                    trace_id=p.trace_id)
        tr.add_span("serve.request", p.t_pc, total,
                    trace_id=p.trace_id, kind=op)

    def _next_dispatch(self) -> int:
        self._dispatch_seq += 1
        return self._dispatch_seq

    # --- worker ---------------------------------------------------------
    def _worker(self) -> None:
        try:
            # device pinning is thread-scoped config: re-enter the
            # replica's default-device context on the worker thread
            # (dispatches happen here, not on the submitting threads)
            with self._device_ctx():
                self._worker_loop()
        except BaseException:
            # an exception ESCAPING the loop (per-request failures are
            # contained above) would kill the worker silently — capture
            # the last moments first (ISSUE 8)
            self.flight.dump("worker_exception", force=True)
            raise

    def _worker_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is _SENTINEL:
                return
            batch = [item]
            deadline = time.monotonic() + self.scfg.batch_window_s
            stop_after = False
            while len(batch) < self.scfg.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _SENTINEL:
                    stop_after = True
                    break
                batch.append(nxt)
            self._note_depth()
            self.telemetry.observe("serve.batch_size", len(batch))
            # ingests first, in arrival order (latest-view semantics:
            # every intraday answer in this micro-batch sees every bar
            # that arrived before the batch was drained)
            ingests = [p for p in batch if isinstance(p.query, Ingest)]
            # discovery jobs (ISSUE 14) run after ingests and BEFORE
            # query groups: a factor registered by this micro-batch's
            # job is queryable by the NEXT request, and a query group
            # dispatched after it already sees the grown name set
            discovers = [p for p in batch
                         if isinstance(p.query, Discover)]
            queries = [p for p in batch
                       if not isinstance(p.query, (Ingest, Discover))]
            groups: Dict[Tuple[int, int], list] = {}
            for p in queries:
                key = ("intraday" if p.query.kind == "intraday"
                       else (p.query.start, p.query.end))
                groups.setdefault(key, []).append(p)
            self.telemetry.gauge("serve.inflight", len(batch))
            for p in ingests:
                self._apply_ingest(p)
            for p in discovers:
                self._apply_discover(p)
            for key, group in groups.items():
                if key == "intraday":
                    self._dispatch_intraday(group)
                else:
                    self._dispatch_group(key, group)
            self.telemetry.gauge("serve.inflight", 0)
            if stop_after:
                return

    def _apply_ingest(self, p: _Pending) -> None:
        """Advance the streaming carry by one Ingest (one scan
        dispatch). A failed ingest fails only its own future but bumps
        the breaker — a stuck feed must shed, not queue unboundedly."""
        tel = self.telemetry
        did = self._next_dispatch()
        t_dispatch = time.monotonic()
        with tel.tracer("serve.ingest", trace_id=p.trace_id):
            try:
                t0 = time.perf_counter()
                self.stream_engine.ingest_minutes(p.query.bars,
                                                  p.query.present)
                ingest_s = time.perf_counter() - t0
                tel.observe("serve.stage_seconds", ingest_s,
                            stage="ingest")
            except Exception as e:  # noqa: BLE001 — per-request + breaker
                p.future.set_exception(e)
                tel.counter("serve.failures", stage="ingest")
                self._complete(p, "ingest", "error", did, 1,
                               time.perf_counter() - t0, 0.0,
                               t_dispatch, error=e)
                self._breaker_failure()
                return
            p.future.set_result({
                "trace_id": p.trace_id,
                "minute": self.stream_engine.minutes,
                "bars": int(p.query.present.sum())})
            tel.observe("serve.request_seconds",
                        time.monotonic() - p.t_enqueue, kind="ingest")
            self._complete(p, "ingest", "ok", did, 1, ingest_s, 0.0,
                           t_dispatch)
        self.flight.note_dispatch({"dispatch_id": did, "op": "ingest",
                                   "minute": self.stream_engine.minutes})
        tel.hbm.sample("serve.ingest")
        self._breaker_ok()

    def _reload_discoveries(self) -> int:
        """Reload persisted ``disc_*.json`` records from
        ``research_dir`` into ``research/registry`` and this server's
        factor universe (construction-time; no worker is running yet,
        so growing ``self.names`` here is single-threaded). Corrupted
        records are skipped loudly — one bad file must not take the
        server down. Returns the number of reloaded records."""
        import glob as _glob
        import os as _os

        from ..research import registry as research_registry
        from ..utils.logging import get_logger
        n = 0
        for path in sorted(_glob.glob(_os.path.join(
                self.scfg.research_dir, "disc_*.json"))):
            try:
                rec = research_registry.load_record(path)
            except (OSError, ValueError, KeyError) as e:
                get_logger(__name__).warning(
                    "skipping unloadable discovery record %s: %s",
                    path, e)
                self.telemetry.counter("discover.reload_failures")
                continue
            research_registry.register_genome(
                rec.genome, rec.skeleton, fitness=rec.fitness,
                mean_ic=rec.mean_ic, mean_rank_ic=rec.mean_rank_ic,
                spread=rec.spread, generations=rec.generations,
                pop=rec.pop, data_fingerprint=rec.data_fingerprint,
                telemetry=self.telemetry)
            if rec.name not in self.names:
                self.names = self.names + (rec.name,)
                self.engine.names = self.names
            self.telemetry.counter("discover.reloaded")
            n += 1
        return n

    def _apply_discover(self, p: _Pending) -> None:
        """Run one bounded-generations discovery job (ISSUE 14):
        prepare + warm the fitness executable (compiles land HERE,
        before the generation loop — the job's measured
        ``compiles_during_loop`` must be 0), evolve, register the
        best genome into the live factor universe, and invalidate the
        exposure cache (cached blocks predate the new name and hold
        the wrong ``[F]`` extent). A failed job fails only its own
        future but bumps the breaker, like ingest."""
        from ..research import fitness as research_fitness
        from ..research import registry as research_registry
        from ..research.evolve import resolve_skeleton
        tel = self.telemetry
        did = self._next_dispatch()
        t_dispatch = time.monotonic()
        d: Discover = p.query
        with tel.tracer("serve.discover", trace_id=p.trace_id):
            t0 = time.perf_counter()
            try:
                bars, mask = self.source.slab(d.start, d.end)
                fwd_ret, fwd_valid = \
                    research_fitness.host_forward_returns(
                        bars, mask, d.horizon)
                eng = self.research_engine
                eng.skeleton = resolve_skeleton(d.skeleton)
                data = eng.prepare(bars, mask, fwd_ret, fwd_valid,
                                   horizon=d.horizon)
                eng.warmup(data, d.pop)
                result = eng.evolve(
                    data, pop=d.pop, generations=d.generations,
                    rng=np.random.default_rng(d.seed))
                rec = research_registry.register_genome(
                    result.genome, result.skeleton,
                    fitness=result.fitness, mean_ic=result.mean_ic,
                    mean_rank_ic=result.mean_rank_ic,
                    spread=result.spread,
                    generations=result.generations, pop=result.pop,
                    data_fingerprint=result.fingerprint,
                    save_dir=self.scfg.research_dir, telemetry=tel)
                if rec.name not in self.names:
                    # atomic tuple swap: submit-side validation reads
                    # self.names without the state lock. The engine's
                    # copy grows with it (block builds trace over
                    # engine.names; both writes happen on the worker
                    # thread, the only thread that dispatches), and
                    # cached blocks are dropped — they predate the new
                    # name and hold the wrong [F] extent.
                    self.names = self.names + (rec.name,)
                    self.engine.names = self.names
                    self.cache.clear()
                job_s = time.perf_counter() - t0
                tel.observe("serve.stage_seconds", job_s,
                            stage="discover")
            except Exception as e:  # noqa: BLE001 — per-job + breaker
                p.future.set_exception(e)
                tel.counter("serve.failures", stage="discover")
                self._complete(p, "discover", "error", did, 1,
                               time.perf_counter() - t0, 0.0,
                               t_dispatch, error=e)
                self._breaker_failure()
                return
            record_path = None
            if self.scfg.research_dir:
                import os as _os
                record_path = _os.path.join(self.scfg.research_dir,
                                            f"{rec.name}.json")
            p.future.set_result({
                "trace_id": p.trace_id,
                "name": rec.name,
                "describe": rec.description,
                "fitness": result.fitness,
                "mean_ic": result.mean_ic,
                "mean_rank_ic": result.mean_rank_ic,
                "spread": result.spread,
                "generations": result.generations,
                "pop": result.pop,
                "n_shards": result.n_shards,
                "syncs_per_generation": result.syncs_per_generation,
                "compiles_during_loop": result.compiles_during_loop,
                "history": [round(h, 6) for h in result.history],
                "record_path": record_path,
            })
            tel.observe("serve.request_seconds",
                        time.monotonic() - p.t_enqueue, kind="discover")
            self._complete(p, "discover", "ok", did, 1, job_s, 0.0,
                           t_dispatch)
        self.flight.note_dispatch({"dispatch_id": did, "op": "discover",
                                   "name": rec.name,
                                   "generations": result.generations})
        tel.hbm.sample("serve.discover")
        self._breaker_ok()

    def _dispatch_intraday(self, group: list) -> None:
        """ONE warm snapshot dispatch (+ one host fetch) answers every
        intraday request in ``group`` — the same coalescing contract as
        the block path, over the live carry instead of a cached
        block."""
        tel = self.telemetry
        did = self._next_dispatch()
        t_dispatch = time.monotonic()
        with tel.tracer("serve.dispatch"):
            block_s = 0.0
            try:
                t0 = time.perf_counter()
                if self.scfg.result_wire:
                    # one fused finalize+encode(+stats) dispatch; the
                    # answer is the host dequantize of the fetched
                    # payload, and the per-factor quality sketch rode
                    # the same fetch (ISSUE 12)
                    from ..data import result_wire as _rw
                    eng = self.stream_engine
                    payload, ready, st = eng.snapshot_wire_stats()
                    pay = np.asarray(payload)   # the boundary sync
                    rdy = np.asarray(ready)
                    exp, _v = _rw.decode_block(
                        pay, len(eng.names), 1, eng.n_tickers,
                        eng.result_spec.spill_rows,
                        telemetry=self.telemetry,
                        names=eng.names)
                    exp = exp[:, 0, :]
                    self.telemetry.counter("serve.result_wire_answers")
                    self.telemetry.counter("serve.result_wire_bytes",
                                           _v["payload_bytes"])
                else:
                    exposures, ready, st = \
                        self.stream_engine.snapshot_stats()
                    exp = np.asarray(exposures)   # the boundary sync
                    rdy = np.asarray(ready)
                block_s = time.perf_counter() - t0
                # factor-health sample (ISSUE 12): fused stats +
                # per-factor readiness fraction + the carry's minute —
                # the stream's data-level lag signal
                tel.factorplane.observe_stream(
                    self.stream_engine.names, st,
                    ready_frac=rdy.mean(axis=1),
                    minute=self.stream_engine.minutes,
                    boundary="serve.intraday")
                tel.observe("serve.stage_seconds", block_s,
                            stage="block")
            except Exception as e:  # noqa: BLE001 — fail the group, shed
                block_s = time.perf_counter() - t0
                for p in group:
                    p.future.set_exception(e)
                    self._complete(p, "intraday", "error", did,
                                   len(group), block_s, 0.0, t_dispatch,
                                   error=e)
                tel.counter("serve.failures", stage="block")
                self._breaker_failure()
                return
            if len(group) > 1:
                tel.counter("serve.coalesced_dispatches")
                tel.counter("serve.coalesced_requests", len(group))
            minute = self.stream_engine.minutes
            ok = True
            for p in group:
                t0 = time.perf_counter()
                try:
                    result = self._answer_intraday(exp, rdy, minute,
                                                   p.query)
                except Exception as e:  # noqa: BLE001 — per-request
                    p.future.set_exception(e)
                    tel.counter("serve.failures", stage="answer")
                    self._complete(p, "intraday", "error", did,
                                   len(group), block_s,
                                   time.perf_counter() - t0,
                                   t_dispatch, error=e)
                    ok = False
                    continue
                result["trace_id"] = p.trace_id
                p.future.set_result(result)
                now = time.monotonic()
                answer_s = time.perf_counter() - t0
                tel.observe("serve.stage_seconds", answer_s,
                            stage="answer")
                tel.observe("serve.stage_seconds",
                            t_dispatch - p.t_enqueue, stage="queue_wait")
                tel.observe("serve.request_seconds", now - p.t_enqueue,
                            kind="intraday")
                self._complete(p, "intraday", "ok", did, len(group),
                               block_s, answer_s, t_dispatch)
        self.flight.note_dispatch({"dispatch_id": did, "op": "intraday",
                                   "group_size": len(group),
                                   "block_s": round(block_s, 6)})
        tel.hbm.sample("serve.dispatch")
        if ok:
            self._breaker_ok()
        else:
            self._breaker_failure()

    def _answer_intraday(self, exp: np.ndarray, rdy: np.ndarray,
                         minute: int, q: Query) -> dict:
        # index by the STREAM engine's names: the snapshot's [F, T]
        # rows follow its construction-time set, which a later
        # discovery registration never grows (see _validate)
        stream_names = self.stream_engine.names
        names = q.names or stream_names
        idx = [stream_names.index(n) for n in names]
        return {
            "minute": minute,
            "codes": list(self.source.codes),
            "exposures": {n: exp[i].tolist()
                          for n, i in zip(names, idx)},
            # readiness is the SOUND gate (docs/streaming.md): False
            # means the kernel's defining group is still empty at this
            # minute; True with NaN means degenerate data, not absence
            "ready": {n: rdy[i].tolist() for n, i in zip(names, idx)},
        }

    def _dispatch_group(self, key: Tuple[int, int], group: list) -> None:
        """One device block answers every request in ``group`` — the
        coalescing contract. A block failure fails the whole group and
        bumps the breaker once."""
        tel = self.telemetry
        did = self._next_dispatch()
        t_dispatch = time.monotonic()
        with tel.tracer("serve.dispatch"):
            block_s = 0.0
            cached = False
            try:
                t0 = time.perf_counter()
                block = self.cache.get(key)
                cached = block is not None
                if block is None:
                    bars, mask = self.source.slab(*key)
                    block = self.engine.build_block(bars, mask)
                    self.cache.put(key, block)
                    tel.counter("serve.dispatches")
                block_s = time.perf_counter() - t0
                tel.observe("serve.stage_seconds", block_s,
                            stage="block")
            except Exception as e:  # noqa: BLE001 — fail the group, shed
                block_s = time.perf_counter() - t0
                for p in group:
                    p.future.set_exception(e)
                    self._complete(p, p.query.kind, "error", did,
                                   len(group), block_s, 0.0, t_dispatch,
                                   error=e)
                tel.counter("serve.failures", stage="block")
                self._breaker_failure()
                return
            if len(group) > 1:
                tel.counter("serve.coalesced_dispatches")
                tel.counter("serve.coalesced_requests", len(group))
            if not cached and block.get("stats") is not None:
                # factor-health sample (ISSUE 12): the fused [F, 9]
                # sketch rode the block's own module — one sample per
                # block BUILD (cache hits re-serve already-observed
                # data). Materializing it here fronts the same block
                # wait the first answer's fetch pays; no extra wall
                tel.factorplane.observe_block(self.names,
                                              block["stats"],
                                              boundary="serve.block")
            fetched: dict = {}
            ok = True
            for p in group:
                t0 = time.perf_counter()
                try:
                    result = self._answer(block, p.query, fetched)
                except Exception as e:  # noqa: BLE001 — per-request
                    p.future.set_exception(e)
                    tel.counter("serve.failures", stage="answer")
                    self._complete(p, p.query.kind, "error", did,
                                   len(group), block_s,
                                   time.perf_counter() - t0,
                                   t_dispatch, error=e)
                    ok = False
                    continue
                result["trace_id"] = p.trace_id
                p.future.set_result(result)
                now = time.monotonic()
                answer_s = time.perf_counter() - t0
                tel.observe("serve.stage_seconds", answer_s,
                            stage="answer")
                tel.observe("serve.stage_seconds",
                            t_dispatch - p.t_enqueue, stage="queue_wait")
                tel.observe("serve.request_seconds", now - p.t_enqueue,
                            kind=p.query.kind)
                self._complete(p, p.query.kind, "ok", did, len(group),
                               block_s, answer_s, t_dispatch)
        self.flight.note_dispatch({
            "dispatch_id": did, "op": "block", "key": list(key),
            "group_size": len(group), "cache_hit": cached,
            "block_s": round(block_s, 6)})
        tel.hbm.sample("serve.dispatch")
        # micro-batch fill at the serve dispatch boundary (ISSUE 9):
        # coalesced requests per dispatch vs the configured ceiling
        tel.meshplane.record_occupancy(
            len(group) / max(1, self.scfg.max_batch),
            boundary="serve.dispatch")
        if ok:
            self._breaker_ok()
        else:
            self._breaker_failure()

    # --- answers (the boundary: device block -> host JSON-able) ---------
    def _days_codes(self, q: Query) -> dict:
        return {"days": list(self.source.days[q.start:q.end]),
                "start": q.start, "end": q.end}

    def _host_exposures(self, block, fetched: dict) -> np.ndarray:
        """The group's ONE host fetch of the stacked exposures (memoised
        across the group's factors-queries) — the declared GL-A3
        boundary sync of the request loop. With
        ``ServeConfig.result_wire`` the fetch ships the blocked-
        quantized payload instead of raw f32 (~half the bytes over the
        tunnel) and the answer is its host dequantize — byte-identical
        to decoding the same payload anywhere else, and re-encoded from
        the RAW cached block on every dispatch group (never from a
        decode: no double quantization through the exposure cache)."""
        if "exposures" not in fetched:
            if self.scfg.result_wire:
                from ..data import result_wire as _rw
                payload_dev, spec = self.engine.encode_exposures(block)
                payload = np.asarray(payload_dev)  # the boundary sync
                f, d, t = block["exposures"].shape
                dec, v = _rw.decode_block(
                    payload, f, d, t, spec.spill_rows,
                    telemetry=self.telemetry)
                self.telemetry.counter("serve.result_wire_answers")
                self.telemetry.counter("serve.result_wire_bytes",
                                       v["payload_bytes"])
                fetched["exposures"] = dec
            else:
                fetched["exposures"] = np.asarray(block["exposures"])
        return fetched["exposures"]

    def _wire_payload(self, block, fetched: dict):
        """The group's ONE host fetch of the PACKED result-wire payload
        (memoised beside the decoded-exposures memo — a mixed group of
        wire and json factors-queries pays at most one fetch of each).
        Encodes from the cached RAW f32 block (never from a decode; no
        double quantization) on a warm executable, so steady-state wire
        traffic compiles nothing."""
        if "wire" not in fetched:
            payload_dev, spec = self.engine.encode_exposures(block)
            payload = np.asarray(payload_dev)  # the boundary sync
            self.telemetry.counter("serve.result_wire_answers")
            self.telemetry.counter("serve.result_wire_bytes",
                                   int(payload.nbytes))
            fetched["wire"] = (payload, spec)
        return fetched["wire"]

    def _answer(self, block, q: Query, fetched: dict) -> dict:
        out = self._days_codes(q)
        if q.kind == "factors" and q.encoding == "wire":
            payload, spec = self._wire_payload(block, fetched)
            f, d, t = block["exposures"].shape
            # the payload travels VERBATIM: the HTTP edge frames these
            # bytes (data/result_wire.pack_frame) and the client-side
            # dequantize (serve/wireclient.py) is byte-identical to
            # decoding the same payload here
            out.pop("days", None)
            out.update({
                "wire": True, "payload": payload,
                "n_factors": f, "days": d, "tickers": t,
                "spill_rows": spec.spill_rows,
                "names": list(self.names)})
            return out
        if q.kind == "factors":
            exp = self._host_exposures(block, fetched)
            names = q.names or self.names
            out["codes"] = list(self.source.codes)
            out["exposures"] = {
                n: exp[self.names.index(n)].tolist() for n in names}
            return out
        if q.kind == "ic":
            ic, rank_ic = self.engine.ic(block, q.factor, q.horizon)
            ic = np.asarray(ic)
            rank_ic = np.asarray(rank_ic)
            out.update({
                "factor": q.factor, "horizon": q.horizon,
                "ic": ic.tolist(), "rank_ic": rank_ic.tolist(),
                "mean_ic": _finite_mean(ic),
                "mean_rank_ic": _finite_mean(rank_ic)})
            # realized-IC health (ISSUE 12): the existing AOT IC graph
            # already produced the number whenever horizon data was
            # available — the plane only rolls it per (factor, horizon)
            self.telemetry.factorplane.note_ic(
                q.factor, out["mean_ic"], horizon=q.horizon)
            return out
        _labels, counts, mean_ret = self.engine.decile(
            block, q.factor, q.horizon, q.group_num)
        out.update({
            "factor": q.factor, "horizon": q.horizon,
            "group_num": q.group_num,
            "counts": np.asarray(counts).tolist(),
            "mean_fwd_ret": np.asarray(mean_ret).tolist()})
        return out


def _finite_mean(x: np.ndarray):
    f = x[np.isfinite(x)]
    return round(f.mean().tolist(), 8) if f.size else None


class ServeClient:
    """In-process client API — the notebook-facing surface. Each method
    submits one :class:`Query` and blocks on its future."""

    def __init__(self, server: FactorServer,
                 timeout: Optional[float] = 60.0):
        self._server = server
        self._timeout = timeout

    def factors(self, start: int, end: int,
                names: Optional[Sequence[str]] = None) -> dict:
        q = Query("factors", start, end,
                  names=tuple(names) if names else None)
        return self._server.submit(q).result(self._timeout)

    def factors_wire(self, start: int, end: int):
        """The full factor block over ``[start, end)`` through the
        result wire (ISSUE 20): submits ``encoding='wire'`` and decodes
        the packed payload with the first-party decoder
        (:mod:`.wireclient`) — the same dequantize an HTTP wire client
        runs, so in-process and edge answers are byte-identical by
        construction. Returns ``(exposures [F, D, T], meta)``."""
        from .wireclient import decode_answer
        q = Query("factors", start, end, encoding="wire")
        ans = self._server.submit(q).result(self._timeout)
        return decode_answer(ans, telemetry=self._server.telemetry)

    def ic(self, factor: str, start: int, end: int,
           horizon: int = 1) -> dict:
        q = Query("ic", start, end, factor=factor, horizon=horizon)
        return self._server.submit(q).result(self._timeout)

    def decile(self, factor: str, start: int, end: int,
               horizon: int = 1, group_num: int = 5) -> dict:
        q = Query("decile", start, end, factor=factor, horizon=horizon,
                  group_num=group_num)
        return self._server.submit(q).result(self._timeout)

    def ingest(self, bars, present) -> dict:
        """Advance the streaming carry by ``B`` minutes of bars;
        returns ``{"minute", "bars"}`` once applied (ISSUE 7)."""
        return self._server.ingest(bars, present).result(self._timeout)

    def intraday(self, names: Optional[Sequence[str]] = None) -> dict:
        """The live partial-day exposures + readiness plane (ISSUE
        7)."""
        q = Query("intraday", names=tuple(names) if names else None)
        return self._server.submit(q).result(self._timeout)

    def discover(self, start: int, end: int, generations: int = 4,
                 pop: int = 128, seed: int = 0, horizon: int = 1,
                 skeleton: str = "default") -> dict:
        """Run a bounded-generations discovery job and block for its
        answer (the registered name + backtest stats; ISSUE 14)."""
        return self._server.discover(
            start, end, generations=generations, pop=pop, seed=seed,
            horizon=horizon, skeleton=skeleton).result(self._timeout)

    def factor_list(self) -> dict:
        """Built-in + discovered factor names (``GET /v1/factors``)."""
        return self._server.factor_list()
