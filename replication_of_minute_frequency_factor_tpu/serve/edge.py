"""Evented binary front door: one selectors loop, many connections.

ISSUE 20's tentpole. The legacy binding (:mod:`.http`) spends one
thread and one short-lived connection per request and re-inflates every
answer to JSON text; this module replaces the transport on the
query/ingest hot path with a single non-blocking event loop
(:mod:`selectors`) that owns accept/read/write for EVERY connection:

* **Persistent keep-alive connections** — HTTP/1.1 keep-alive is the
  default; a connection serves any number of requests until the client
  closes it or goes idle past ``ServeConfig.edge_idle_timeout_s``
  (the slow-loris bound: a peer that dribbles half a request forever
  is reaped, never parked on a blocked thread).
* **Pipelined request multiplexing** — a client may write request N+1
  before answer N arrives. Requests are dispatched to the server's
  micro-batching queue as they parse (so pipelined queries COALESCE),
  and responses flush strictly in request order per connection.
* **The result wire end to end** — ``POST /v1/query`` with ``Accept:
  application/x-mff-wire`` answers with the packed result-wire payload
  verbatim (framed by :func:`..data.result_wire.pack_frame`), through
  the same :func:`.http.query_from_doc` / :func:`.http.render_answer`
  pair the legacy binding uses. :mod:`.wireclient` is the first-party
  decoder.
* **Chunked range streaming** — a wire factors query carrying
  ``"chunk_days": N`` splits its day range into N-day sub-queries
  submitted upfront; each framed sub-answer flushes as its OWN
  ``Transfer-Encoding: chunked`` chunk the moment its dispatch
  completes (completion order — frames are self-describing, the
  client reassembles by each frame's ``start``). A mid-stream dispatch
  failure aborts the connection (chunked HTTP has no late error
  channel); ``edge.stream_aborts`` counts those.
* **Per-tenant admission quotas** — a token bucket per tenant key
  (``X-Tenant``, else ``X-API-Key``, else ``"anon"``) layered ABOVE
  pod admission, armed by ``ServeConfig.tenant_quota_rps``; refusals
  are ``429`` with the same ``Retry-After`` contract the shed ladder
  uses (:func:`.http.retry_after_seconds`).

Threading contract (graftlint Tier C, declared below): the event loop
is SINGLE-THREADED BY DESIGN — exactly one loop thread touches
sockets, connection parse/flush state and the selector. The shared
state crossing threads is declared and guarded by ``_edge_lock``:
``_edge_conns`` (the connection table: loop thread mutates, dispatch
callbacks only consult liveness through the ready queue),
``_edge_ready`` (completions enqueued by worker/aux threads, drained
by the loop), and ``_edge_quota`` (token buckets). The one auxiliary
thread exists because some backend posts are synchronous by contract
(fleet ingest fan-out, flight dumps) and must not stall the loop.

Telemetry taxonomy (docs/observability.md): ``edge.open_connections``,
``edge.conns_opened`` / ``edge.conns_closed{reason=}``,
``edge.requests{method=}``, ``edge.pipelined_depth``,
``edge.answers{encoding=}``, ``edge.bytes_in`` /
``edge.bytes_out{encoding=}``, ``edge.chunks`` /
``edge.chunk_flush_seconds``, ``edge.quota_rejected{tenant=}``,
``edge.http_errors{code=}``, ``edge.stream_aborts``,
``edge.orphan_answers``, ``edge.loop_errors{error=}``.
"""

from __future__ import annotations

import dataclasses
import json
import queue
import selectors
import socket
import threading
import time
import urllib.parse
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

from ..telemetry.opsplane import canonical_trace_id
from .http import (MAX_BODY_BYTES, MAX_INGEST_BODY_BYTES,
                   WIRE_CONTENT_TYPE, get_payload, query_from_doc,
                   render_answer, retry_after_seconds)
from .service import FactorServer, LoadShedError, Query

#: graftlint Tier C lock-discipline contract (analysis/concurrency_tier
#: GL-C1..C4; runtime twin telemetry/lockcheck under MFF_LOCK_ASSERT=1).
#: The loop thread owns sockets and per-connection state WITHOUT a lock
#: — that is the single-threaded-by-design part — so only the state
#: that crosses threads is guarded: the connection table (consulted
#: when draining completions), the completion queue (written by
#: executor/aux threads), and the tenant token buckets.
GLC_CONTRACT = {
    "EdgeServer": {
        "lock": "_edge_lock",
        "guards": ("_edge_conns", "_edge_ready", "_edge_quota"),
        "init": (),
        "locked": (),
    },
}

#: request line + header block bound (the legacy stdlib server's own
#: default header limit is 64 KiB over 100 lines; one bound here)
MAX_HEADER_BYTES = 32768

#: per-readable-event socket read size
_RECV_CHUNK = 1 << 18

_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found", 405: "Method Not "
    "Allowed", 409: "Conflict", 413: "Payload Too Large",
    429: "Too Many Requests", 500: "Internal Server Error",
    503: "Service Unavailable", 505: "HTTP Version Not Supported",
}


class _BadRequest(Exception):
    """Protocol-level malformation: answer ``status`` and close."""

    def __init__(self, status: int, msg: str):
        super().__init__(msg)
        self.status = status


def format_response(status: int, ctype: str, body: bytes, *,
                    trace_id: Optional[str] = None,
                    retry_after_s: Optional[float] = None,
                    close: bool = False) -> bytes:
    """One buffered HTTP/1.1 response, bytes-complete (the loop never
    partially materializes a response — partial WRITES are the
    socket's business, handled by the out-buffer)."""
    head = [
        f"HTTP/1.1 {status} {_REASONS.get(status, 'Unknown')}",
        f"Content-Type: {ctype}",
        f"Content-Length: {len(body)}",
    ]
    if trace_id:
        head.append(f"X-Trace-Id: {trace_id}")
    if retry_after_s is not None:
        head.append(f"Retry-After: {retry_after_seconds(retry_after_s)}")
    head.append("Connection: close" if close else
                "Connection: keep-alive")
    return ("\r\n".join(head) + "\r\n\r\n").encode("latin-1") + body


class _Stream:
    """A chunked-response slot: sub-answers land out of order, flush
    as chunks in completion order, terminate when all are in."""

    __slots__ = ("pending", "chunks", "failed", "headers_sent", "tid",
                 "t0")

    def __init__(self, pending: int, tid: Optional[str], t0: float):
        self.pending = pending
        self.chunks: deque = deque()
        self.failed = False
        self.headers_sent = False
        self.tid = tid
        self.t0 = t0


class _Conn:
    """Per-connection state. Loop-thread-only by design (Tier C: the
    contract guards the TABLE of these, not their insides)."""

    __slots__ = ("sock", "cid", "inbuf", "out", "slots", "next_slot",
                 "head", "t_last", "want_close", "events")

    def __init__(self, sock: socket.socket, cid: int):
        self.sock = sock
        self.cid = cid
        self.inbuf = bytearray()
        self.out = bytearray()
        #: slot -> None (pending) | bytes (ready) | _Stream
        self.slots: Dict[int, Any] = {}
        self.next_slot = 0
        self.head = 0
        self.t_last = time.monotonic()
        self.want_close = False
        self.events = 0


class ServerEdgeBackend:
    """Adapts one :class:`FactorServer` to the edge's backend protocol:
    ``get`` answers the whole GET surface synchronously (registry
    snapshots — no device work), ``submit_query`` returns the queue
    future, ``post`` maps the remaining POST routes to a future or a
    blocking call the edge runs on its aux thread."""

    label = "serve"

    def __init__(self, server: FactorServer,
                 timeout: Optional[float] = 60.0):
        self.server = server
        self.timeout = timeout

    @property
    def telemetry(self):
        return self.server.telemetry

    def get(self, path: str, query: dict, accept: str
            ) -> Optional[Tuple[int, str, bytes]]:
        return get_payload(self.server, path, query, accept)

    def submit_query(self, q: Query, tid: Optional[str]):
        return self.server.submit(q, trace_id=tid)

    def post(self, path: str, doc: dict, tid: Optional[str]):
        if path == "/v1/ingest":
            return "future", self.server.ingest(
                doc["bars"], doc["present"], trace_id=tid)
        if path == "/v1/discover":
            kwargs = dict(
                start=int(doc["start"]), end=int(doc["end"]),
                generations=int(doc.get("generations", 4)),
                pop=int(doc.get("pop", 128)),
                seed=int(doc.get("seed", 0)),
                horizon=int(doc.get("horizon", 1)),
                skeleton=str(doc.get("skeleton", "default")))
            return "future", self.server.discover(trace_id=tid,
                                                  **kwargs)
        if path == "/v1/debug/dump":
            server = self.server

            def dump():
                p = server.debug_dump()
                if p is None:
                    return 409, {"error": "no flight dump directory "
                                          "configured "
                                          "(ServeConfig.flight_dir)"}
                return 200, {"path": p, "requests": len(server.flight)}

            return "call", dump
        return None

    def max_body(self, path: str) -> int:
        return (MAX_INGEST_BODY_BYTES if path == "/v1/ingest"
                else MAX_BODY_BYTES)


class EdgeServer:
    """The evented front door. One loop thread, one aux thread, N
    persistent connections; see the module docstring for the protocol
    surface and the declared threading contract."""

    def __init__(self, backend, host: str = "127.0.0.1", port: int = 0,
                 *, quota_rps: float = 0.0, quota_burst: float = 0.0,
                 idle_timeout_s: float = 30.0, tick_s: float = 0.25):
        self.backend = backend
        self.telemetry = backend.telemetry
        self.quota_rps = float(quota_rps)
        self.quota_burst = float(quota_burst) if quota_burst > 0 \
            else max(1.0, float(quota_rps))
        self.idle_timeout_s = float(idle_timeout_s)
        self._tick_s = float(tick_s)

        self._edge_lock = threading.Lock()
        self._edge_conns: Dict[int, _Conn] = {}
        self._edge_ready: deque = deque()
        self._edge_quota: Dict[str, Tuple[float, float]] = {}
        self._next_cid = 0
        self._stopping = False

        self._listener = socket.create_server((host, port), backlog=128,
                                              reuse_port=False)
        self._listener.setblocking(False)
        self.server_address = self._listener.getsockname()

        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)

        self._sel = selectors.DefaultSelector()
        self._sel.register(self._listener, selectors.EVENT_READ,
                           "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")

        self._aux_q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="factor-serve-edge")
        self._aux = threading.Thread(target=self._aux_run, daemon=True,
                                     name="factor-edge-aux")
        self._thread.start()
        self._aux.start()
        from ..telemetry.lockcheck import maybe_install
        maybe_install(self)

    # -- lifecycle ----------------------------------------------------

    def close(self) -> None:
        """Stop the loop, join both threads, release every socket."""
        if self._stopping:
            return
        self._stopping = True
        self._wake()
        self._aux_q.put(None)
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)
        if self._aux.is_alive():
            self._aux.join(timeout=10.0)
        for conn in list(self._edge_conns.values()):
            self._close_conn(conn, "shutdown")
        for sock in (self._listener, self._wake_r, self._wake_w):
            try:
                sock.close()
            except OSError:
                self.telemetry.counter("edge.loop_errors",
                                       error="close")
        try:
            self._sel.close()
        except (OSError, RuntimeError):
            self.telemetry.counter("edge.loop_errors",
                                   error="selector_close")

    def shutdown(self) -> None:
        """Alias so callers can hold an ``httpd``-shaped handle
        (:func:`.http.serve_frontdoor` returns either transport)."""
        self.close()

    # -- the loop -----------------------------------------------------

    def _run(self) -> None:
        while not self._stopping:
            try:
                self._loop_once()
            except Exception as e:  # noqa: BLE001 — loop must survive
                self.telemetry.counter("edge.loop_errors",
                                       error=type(e).__name__)

    def _loop_once(self) -> None:
        events = self._sel.select(timeout=self._tick_s)
        for key, mask in events:
            if key.data == "accept":
                self._accept()
            elif key.data == "wake":
                self._drain_wake()
            else:
                conn = key.data
                if mask & selectors.EVENT_READ \
                        and conn.cid in self._edge_conns:
                    self._on_readable(conn)
                if mask & selectors.EVENT_WRITE \
                        and conn.cid in self._edge_conns:
                    self._flush(conn)
        self._drain_ready()
        self._reap_idle(time.monotonic())

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\0")
        except (BlockingIOError, InterruptedError):
            return  # pipe full — the loop is already due to wake
        except OSError:
            return  # shutting down: the loop exits on _stopping

    def _drain_wake(self) -> None:
        while True:
            try:
                if not self._wake_r.recv(4096):
                    return
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self.telemetry.counter("edge.loop_errors",
                                       error="wake_recv")
                return

    def _accept(self) -> None:
        while True:
            try:
                sock, _addr = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self.telemetry.counter("edge.loop_errors",
                                       error="accept")
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            except OSError:
                self.telemetry.counter("edge.loop_errors",
                                       error="nodelay")
            conn = _Conn(sock, self._next_cid)
            self._next_cid += 1
            with self._edge_lock:
                self._edge_conns[conn.cid] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)
            conn.events = selectors.EVENT_READ
            self.telemetry.counter("edge.conns_opened")
            self.telemetry.gauge("edge.open_connections",
                                 float(len(self._edge_conns)))

    def _close_conn(self, conn: _Conn, reason: str) -> None:
        with self._edge_lock:
            live = self._edge_conns.pop(conn.cid, None)
        if live is None:
            return
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            self.telemetry.counter("edge.loop_errors",
                                   error="unregister")
        try:
            conn.sock.close()
        except OSError:
            self.telemetry.counter("edge.loop_errors",
                                   error="sock_close")
        self.telemetry.counter("edge.conns_closed", reason=reason)
        self.telemetry.gauge("edge.open_connections",
                             float(len(self._edge_conns)))

    def _reap_idle(self, now: float) -> None:
        if self.idle_timeout_s <= 0:
            return
        for conn in list(self._edge_conns.values()):
            # only reap connections with no dispatch in flight: an
            # answer the server is still computing is not idleness —
            # a half-written request (slow loris) or an unread
            # response (slow reader) is
            if now - conn.t_last > self.idle_timeout_s \
                    and conn.head == conn.next_slot:
                self._close_conn(conn, "idle")

    # -- reads and protocol parse ------------------------------------

    def _on_readable(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(_RECV_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_conn(conn, "recv_error")
            return
        if not data:
            # peer closed; anything still in flight flushes nowhere
            self._close_conn(conn, "peer_closed")
            return
        conn.t_last = time.monotonic()
        conn.inbuf += data
        self.telemetry.counter("edge.bytes_in", float(len(data)))
        try:
            self._parse_requests(conn)
        except _BadRequest as e:
            self.telemetry.counter("edge.http_errors",
                                   code=str(e.status))
            slot = conn.next_slot
            conn.next_slot += 1
            conn.slots[slot] = format_response(
                e.status, "application/json",
                json.dumps({"error": str(e)}).encode(), close=True)
            conn.want_close = True
            conn.inbuf.clear()
        self._pump(conn)

    def _parse_requests(self, conn: _Conn) -> None:
        while not conn.want_close:
            parsed = self._try_parse(conn)
            if parsed is None:
                return
            self._dispatch(conn, *parsed)

    def _try_parse(self, conn: _Conn
                   ) -> Optional[Tuple[str, str, str, Dict[str, str],
                                       bytes]]:
        """One complete request off ``conn.inbuf``, or None when more
        bytes are needed. Raises :class:`_BadRequest` on protocol
        malformation (answer + close; no resynchronization)."""
        buf = conn.inbuf
        hdr_end = buf.find(b"\r\n\r\n")
        if hdr_end < 0:
            if len(buf) > MAX_HEADER_BYTES:
                raise _BadRequest(400, "header block too large")
            return None
        try:
            text = bytes(buf[:hdr_end]).decode("latin-1")
        except UnicodeDecodeError:
            raise _BadRequest(400, "undecodable header block")
        lines = text.split("\r\n")
        parts = lines[0].split()
        if len(parts) != 3:
            raise _BadRequest(400,
                              f"malformed request line {lines[0]!r}")
        method, target, version = parts
        if version not in ("HTTP/1.1", "HTTP/1.0"):
            raise _BadRequest(505, f"unsupported version {version!r}")
        headers: Dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            key, sep, value = line.partition(":")
            if not sep or not key.strip():
                raise _BadRequest(400, f"malformed header {line!r}")
            headers[key.strip().lower()] = value.strip()
        if "chunked" in headers.get("transfer-encoding", "").lower():
            raise _BadRequest(400, "chunked request bodies are not "
                                   "supported; send Content-Length")
        try:
            length = int(headers.get("content-length", "0") or "0")
        except ValueError:
            raise _BadRequest(400, "malformed Content-Length")
        if length < 0:
            raise _BadRequest(400, "negative Content-Length")
        path = urllib.parse.urlparse(target).path
        if length > self.backend.max_body(path):
            # replying without reading the oversized body only works
            # if we then drop the connection
            raise _BadRequest(413, "body too large")
        body_start = hdr_end + 4
        if len(buf) - body_start < length:
            return None
        body = bytes(buf[body_start:body_start + length])
        del buf[:body_start + length]
        return method, target, version, headers, body

    # -- request dispatch --------------------------------------------

    def _dispatch(self, conn: _Conn, method: str, target: str,
                  version: str, headers: Dict[str, str], body: bytes
                  ) -> None:
        t0 = time.monotonic()
        tel = self.telemetry
        tel.counter("edge.requests", method=method)
        tel.observe("edge.pipelined_depth",
                    float(conn.next_slot - conn.head + 1))
        connection = headers.get("connection", "").lower()
        if connection == "close" or (version == "HTTP/1.0"
                                     and connection != "keep-alive"):
            conn.want_close = True
        slot = conn.next_slot
        conn.next_slot += 1
        conn.slots[slot] = None
        parsed = urllib.parse.urlparse(target)
        if method == "GET":
            res = self.backend.get(parsed.path,
                                   urllib.parse.parse_qs(parsed.query),
                                   headers.get("accept", ""))
            if res is None:
                self._slot_error(conn, slot, 404,
                                 f"no route {parsed.path}", None)
                return
            status, ctype, payload = res
            self._set_slot(conn, slot,
                           format_response(status, ctype, payload))
            if status >= 400:
                tel.counter("edge.http_errors", code=str(status))
            else:
                tel.counter("edge.answers", encoding="json")
                tel.counter("edge.bytes_out", float(len(payload)),
                            encoding="json")
            return
        if method != "POST":
            self._slot_error(conn, slot, 405,
                             f"method {method} not allowed", None)
            return
        self._handle_post(conn, slot, parsed.path, headers, body, t0)

    def _handle_post(self, conn: _Conn, slot: int, path: str,
                     headers: Dict[str, str], body: bytes, t0: float
                     ) -> None:
        tid = canonical_trace_id(headers.get("x-trace-id"))
        if path in ("/v1/query", "/v1/ingest"):
            retry = self._quota_admit(headers)
            if retry is not None:
                self._slot_error(conn, slot, 429,
                                 "tenant quota exceeded", tid,
                                 retry_after_s=retry, quota=True)
                return
        try:
            doc = json.loads(body or b"{}")
            if not isinstance(doc, dict):
                raise ValueError("request body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as e:
            self._slot_error(conn, slot, 400,
                             f"malformed request: {e}", tid)
            return
        if path == "/v1/query":
            self._handle_query(conn, slot, doc, tid,
                               headers.get("accept", ""), t0)
            return
        echo_tid = None if path == "/v1/debug/dump" else tid
        try:
            action = self.backend.post(path, doc, tid)
        except LoadShedError as e:
            self._slot_error(conn, slot, 503, str(e), echo_tid,
                             retry_after_s=e.retry_after_s, shed=True)
            return
        except (KeyError, ValueError, TypeError) as e:
            self._slot_error(conn, slot, 400,
                             f"malformed request: {e}", echo_tid)
            return
        if action is None:
            self._slot_error(conn, slot, 404, f"no route {path}",
                             echo_tid)
            return
        kind, payload = action
        if kind == "future":
            cid = conn.cid
            payload.add_done_callback(
                lambda f: self._async_done(cid, slot,
                                           ("answer", None, echo_tid),
                                           f))
        else:  # "call": synchronous backend work — aux thread's job
            self._aux_q.put((conn.cid, slot, payload, echo_tid))

    def _handle_query(self, conn: _Conn, slot: int, doc: dict,
                      tid: Optional[str], accept: str, t0: float
                      ) -> None:
        try:
            q = query_from_doc(doc, accept)
            chunk_days = int(doc.get("chunk_days") or 0)
            if chunk_days < 0:
                raise ValueError("chunk_days must be >= 0")
            if chunk_days and (q.encoding != "wire"
                               or q.kind != "factors"):
                raise ValueError("chunk_days streams wire-encoded "
                                 "factors queries only")
        except (KeyError, ValueError, TypeError) as e:
            self._slot_error(conn, slot, 400,
                             f"malformed request: {e}", tid)
            return
        if chunk_days and q.end - q.start > chunk_days:
            self._handle_chunked(conn, slot, q, chunk_days, tid, t0)
            return
        try:
            fut = self.backend.submit_query(q, tid)
        except LoadShedError as e:
            self._slot_error(conn, slot, 503, str(e), tid,
                             retry_after_s=e.retry_after_s, shed=True)
            return
        except ValueError as e:
            self._slot_error(conn, slot, 400, str(e), tid)
            return
        cid = conn.cid
        fut.add_done_callback(
            lambda f: self._async_done(cid, slot, ("answer", q, tid),
                                       f))

    def _handle_chunked(self, conn: _Conn, slot: int, q: Query,
                        chunk_days: int, tid: Optional[str], t0: float
                        ) -> None:
        """Split ``[start, end)`` into ``chunk_days``-day sub-queries,
        submit them ALL before streaming starts (admission is
        all-or-nothing: a shed before the first byte is still a clean
        503), then stream each framed sub-answer as it completes."""
        ranges = [(s, min(s + chunk_days, q.end))
                  for s in range(q.start, q.end, chunk_days)]
        futs = []
        try:
            for s, e in ranges:
                sub = dataclasses.replace(q, start=s, end=e)
                futs.append((sub,
                             self.backend.submit_query(sub, tid)))
        except LoadShedError as err:
            self._slot_error(conn, slot, 503, str(err), tid,
                             retry_after_s=err.retry_after_s,
                             shed=True)
            return
        except ValueError as err:
            self._slot_error(conn, slot, 400, str(err), tid)
            return
        conn.slots[slot] = _Stream(len(futs), tid, t0)
        cid = conn.cid
        for sub, fut in futs:
            fut.add_done_callback(
                lambda f, sub=sub: self._async_done(
                    cid, slot, ("chunk", sub, tid), f))

    # -- completion plumbing -----------------------------------------

    def _async_done(self, cid: int, slot: int, ctx: tuple,
                    payload) -> None:
        """Runs on WHICHEVER thread resolves the work (executor
        callback, aux thread, or inline when already done): park the
        completion for the loop and wake it. The only cross-thread
        write, and it is guarded."""
        with self._edge_lock:
            self._edge_ready.append((cid, slot, ctx, payload))
        self._wake()

    def _aux_run(self) -> None:
        """The auxiliary worker: synchronous backend posts (fleet
        ingest fan-out, flight dumps) run here so the loop thread
        never blocks on them."""
        while True:
            item = self._aux_q.get()
            if item is None:
                return
            cid, slot, call, tid = item
            try:
                result = call()
            except Exception as e:  # noqa: BLE001 — mapped to HTTP
                result = e
            self._async_done(cid, slot, ("call", None, tid), result)

    def _drain_ready(self) -> None:
        while True:
            with self._edge_lock:
                if not self._edge_ready:
                    return
                cid, slot, ctx, payload = self._edge_ready.popleft()
            conn = self._edge_conns.get(cid)
            if conn is None or slot not in conn.slots:
                self.telemetry.counter("edge.orphan_answers")
                continue
            kind, q, tid = ctx
            if kind == "chunk":
                self._finish_chunk(conn, slot, q, tid, payload)
            elif kind == "call":
                self._finish_call(conn, slot, tid, payload)
            else:
                self._finish_answer(conn, slot, q, tid, payload)
            self._pump(conn)

    def _finish_answer(self, conn: _Conn, slot: int,
                       q: Optional[Query], tid: Optional[str],
                       fut) -> None:
        e = fut.exception()
        if isinstance(e, LoadShedError):
            self._slot_error(conn, slot, 503, str(e), tid,
                             retry_after_s=e.retry_after_s, shed=True)
            return
        if e is not None:
            self._slot_error(conn, slot, 500,
                             f"{type(e).__name__}: {e}", tid)
            return
        result = fut.result()
        try:
            if q is None:
                ctype, body = ("application/json",
                               json.dumps(result).encode())
            else:
                ctype, body = render_answer(result, q)
        except Exception as err:  # noqa: BLE001 — render failure
            self._slot_error(conn, slot, 500,
                             f"{type(err).__name__}: {err}", tid)
            return
        enc = "wire" if ctype == WIRE_CONTENT_TYPE else "json"
        self.telemetry.counter("edge.answers", encoding=enc)
        self.telemetry.counter("edge.bytes_out", float(len(body)),
                               encoding=enc)
        self._set_slot(conn, slot,
                       format_response(200, ctype, body,
                                       trace_id=tid))

    def _finish_call(self, conn: _Conn, slot: int,
                     tid: Optional[str], result) -> None:
        if isinstance(result, LoadShedError):
            self._slot_error(conn, slot, 503, str(result), tid,
                             retry_after_s=result.retry_after_s,
                             shed=True)
            return
        if isinstance(result, (KeyError, ValueError, TypeError)):
            self._slot_error(conn, slot, 400,
                             f"malformed request: {result}", tid)
            return
        if isinstance(result, BaseException):
            self._slot_error(conn, slot, 500,
                             f"{type(result).__name__}: {result}", tid)
            return
        status, doc = result
        body = json.dumps(doc).encode()
        if status >= 400:
            self.telemetry.counter("edge.http_errors",
                                   code=str(status))
        else:
            self.telemetry.counter("edge.answers", encoding="json")
            self.telemetry.counter("edge.bytes_out", float(len(body)),
                                   encoding="json")
        self._set_slot(conn, slot,
                       format_response(status, "application/json",
                                       body, trace_id=tid))

    def _finish_chunk(self, conn: _Conn, slot: int, sub_q: Query,
                      tid: Optional[str], fut) -> None:
        state = conn.slots.get(slot)
        if not isinstance(state, _Stream):
            self.telemetry.counter("edge.orphan_answers")
            return
        state.pending -= 1
        e = fut.exception()
        if e is not None:
            state.failed = True
            self.telemetry.counter("edge.stream_aborts",
                                   error=type(e).__name__)
            return
        try:
            ctype, frame = render_answer(fut.result(), sub_q)
            if ctype != WIRE_CONTENT_TYPE:
                raise ValueError("chunked sub-answer was not "
                                 "wire-encoded")
        except Exception as err:  # noqa: BLE001 — abort the stream
            state.failed = True
            self.telemetry.counter("edge.stream_aborts",
                                   error=type(err).__name__)
            return
        state.chunks.append(frame)
        self.telemetry.counter("edge.chunks")
        self.telemetry.counter("edge.bytes_out", float(len(frame)),
                               encoding="wire")

    # -- response assembly and writes --------------------------------

    def _set_slot(self, conn: _Conn, slot: int, data: bytes) -> None:
        conn.slots[slot] = data

    def _slot_error(self, conn: _Conn, slot: int, status: int,
                    msg: str, tid: Optional[str], *,
                    retry_after_s: Optional[float] = None,
                    shed: bool = False, quota: bool = False) -> None:
        doc: Dict[str, Any] = {"error": msg}
        if shed:
            doc["shed"] = True
        if quota:
            doc["quota"] = True
        self.telemetry.counter("edge.http_errors", code=str(status))
        self._set_slot(conn, slot, format_response(
            status, "application/json", json.dumps(doc).encode(),
            trace_id=tid, retry_after_s=retry_after_s))

    def _pump(self, conn: _Conn) -> None:
        """Move completed responses into the out-buffer IN SLOT ORDER
        (pipelined answers never reorder on the wire), flushing a
        streaming slot's ready chunks as they exist."""
        while conn.head < conn.next_slot:
            state = conn.slots.get(conn.head)
            if state is None:
                break  # head-of-line answer still in flight
            if isinstance(state, (bytes, bytearray)):
                conn.out += state
                del conn.slots[conn.head]
                conn.head += 1
                continue
            # _Stream
            if not state.headers_sent:
                head = ["HTTP/1.1 200 OK",
                        f"Content-Type: {WIRE_CONTENT_TYPE}",
                        "Transfer-Encoding: chunked"]
                if state.tid:
                    head.append(f"X-Trace-Id: {state.tid}")
                head.append("Connection: keep-alive")
                conn.out += ("\r\n".join(head)
                             + "\r\n\r\n").encode("latin-1")
                state.headers_sent = True
            while state.chunks:
                frame = state.chunks.popleft()
                conn.out += (f"{len(frame):x}\r\n".encode("latin-1")
                             + frame + b"\r\n")
                self.telemetry.observe("edge.chunk_flush_seconds",
                                       time.monotonic() - state.t0)
            if state.failed:
                # chunked HTTP has no mid-stream error channel: the
                # only honest signal is an aborted connection (the
                # client sees a missing terminating chunk)
                self._close_conn(conn, "stream_abort")
                return
            if state.pending == 0:
                conn.out += b"0\r\n\r\n"
                del conn.slots[conn.head]
                conn.head += 1
                continue
            break  # stream open, more sub-answers coming
        self._flush(conn)

    def _flush(self, conn: _Conn) -> None:
        if conn.cid not in self._edge_conns:
            return
        if conn.out:
            try:
                n = conn.sock.send(bytes(conn.out[:1 << 20]))
                if n:
                    del conn.out[:n]
            except (BlockingIOError, InterruptedError):
                n = 0
            except OSError:
                # mid-response disconnect: reap; in-flight answers for
                # this connection become orphans, the worker never
                # blocks on the dead socket
                self._close_conn(conn, "send_error")
                return
        want = selectors.EVENT_READ | (selectors.EVENT_WRITE
                                       if conn.out else 0)
        if want != conn.events:
            try:
                self._sel.modify(conn.sock, want, conn)
                conn.events = want
            except (KeyError, ValueError, OSError):
                self.telemetry.counter("edge.loop_errors",
                                       error="modify")
        if not conn.out and conn.want_close \
                and conn.head == conn.next_slot:
            self._close_conn(conn, "client_close")

    # -- tenant quotas ------------------------------------------------

    def _quota_admit(self, headers: Dict[str, str]
                     ) -> Optional[float]:
        """Token-bucket admission above pod admission: None admits;
        a float is the Retry-After hint (seconds until one token)."""
        rps = self.quota_rps
        if rps <= 0:
            return None
        tenant = (headers.get("x-tenant")
                  or headers.get("x-api-key") or "anon")
        now = time.monotonic()
        with self._edge_lock:
            tokens, t_prev = self._edge_quota.get(tenant,
                                                  (self.quota_burst,
                                                   now))
            tokens = min(self.quota_burst,
                         tokens + (now - t_prev) * rps)
            if tokens >= 1.0:
                self._edge_quota[tenant] = (tokens - 1.0, now)
                return None
            self._edge_quota[tenant] = (tokens, now)
            need = (1.0 - tokens) / rps
        self.telemetry.counter("edge.quota_rejected", tenant=tenant)
        return need


def serve_edge(server: FactorServer, host: str = "127.0.0.1",
               port: int = 0,
               timeout: Optional[float] = 60.0) -> EdgeServer:
    """Bind the evented front door over one :class:`FactorServer`.
    Returns the running :class:`EdgeServer` (``.server_address`` /
    ``.shutdown()``, the same handle shape as the legacy binding);
    quota and idle knobs come from ``ServeConfig``."""
    scfg = server.scfg
    backend = ServerEdgeBackend(server, timeout)
    return EdgeServer(backend, host=host, port=port,
                      quota_rps=scfg.tenant_quota_rps,
                      quota_burst=scfg.tenant_quota_burst,
                      idle_timeout_s=scfg.edge_idle_timeout_s)
