"""serve/ — the long-lived factor service (ISSUE 6).

Every other entry point in this repo is a one-shot CLI: each invocation
pays compile + ingest + teardown to answer a single question. A system
serving "heavy traffic from millions of users" (ROADMAP north star) is a
*resident process*; this package is that process, built on the batch
engine (`pipeline.py`) and the observability stack (PRs 1-2) that was
designed for exactly this request loop:

* :mod:`.executables` — :class:`ExecutableCache`, the keyed AOT
  executable cache generalizing bench's ``_aot_resident`` memo:
  compile-once semantics, every build attributed through
  ``telemetry.attribution.compile_with_telemetry`` (so "did this
  request compile anything" is a registry counter, not a guess);
* :mod:`.expcache` — :class:`DeviceExposureCache`, computed
  ``[F, days, tickers]`` exposure blocks held in device memory under an
  explicit byte budget with LRU eviction and hit/miss/eviction counters;
* :mod:`.engine` — the device-facing compute: fused
  wire-decode + 58-kernel + daily-close graph per day-range block, and
  the IC / decile query graphs, all dispatched through the executable
  cache;
* :mod:`.source` — data sources (:class:`SyntheticSource` for
  bench/tests, :class:`MinuteDirSource` over a directory of day files);
* :mod:`.service` — :class:`FactorServer`: the async request queue that
  micro-batches concurrent queries and COALESCES same-day-range ones
  into one device dispatch, with per-request latency histograms,
  queue-depth/in-flight gauges and a load-shedding circuit breaker;
* :mod:`.http` — a stdlib-only HTTP/JSON binding (``serve_http``),
  plus the shared endpoint library both front doors answer through;
* :mod:`.edge` — the evented binary front door (ISSUE 20): one
  selectors loop, persistent keep-alive connections, pipelined
  multiplexing, the result wire end to end, chunked range streaming,
  per-tenant quotas (``serve_frontdoor`` picks edge vs legacy by
  ``ServeConfig.edge``);
* :mod:`.wireclient` — the first-party result-wire decoder +
  keep-alive :class:`WireClient`.

Streaming (ISSUE 7): ``FactorServer(stream=True)`` additionally owns a
:class:`..stream.engine.StreamEngine` — minute bars ingest through the
same request queue (:class:`Ingest`, ``POST /v1/ingest``) and
``Query(kind="intraday")`` serves the carry's partial-day exposures;
see docs/streaming.md.

Research (ISSUE 14): ``FactorServer(research=True)`` additionally owns
a :class:`..research.evolve.DiscoveryEngine` — ``POST /v1/discover``
runs a bounded-generations evolutionary factor search on the request
queue, the winning genome registers as a live ``disc_<hash>`` factor
name (``GET /v1/factors`` lists built-in + discovered), and the new
name is immediately queryable through ``/v1/query``; see
docs/discovery.md.

Run it: ``python -m replication_of_minute_frequency_factor_tpu serve``
(see docs/serving.md); load-bench it: ``python bench.py serve``.
"""

from __future__ import annotations

from .executables import ExecutableCache
from .expcache import DeviceExposureCache
from .source import MinuteDirSource, SyntheticSource
from .service import (Discover, FactorServer, Ingest, LoadShedError,
                      Query, ServeConfig, ServeClient)
from .http import WIRE_CONTENT_TYPE, serve_frontdoor, serve_http
from .edge import EdgeServer, serve_edge
from .wireclient import WireClient, WireError, decode_answer, \
    decode_frames

__all__ = [
    "DeviceExposureCache", "Discover", "EdgeServer",
    "ExecutableCache", "FactorServer", "Ingest", "LoadShedError",
    "MinuteDirSource", "Query", "ServeClient", "ServeConfig",
    "SyntheticSource", "WIRE_CONTENT_TYPE", "WireClient", "WireError",
    "decode_answer", "decode_frames", "serve_edge", "serve_frontdoor",
    "serve_http",
]
