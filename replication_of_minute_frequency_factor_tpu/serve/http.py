"""Stdlib-only HTTP/JSON binding for :class:`.service.FactorServer`.

Protocol-agnostic by construction: the handler only translates JSON to
:class:`..serve.service.Query` objects and futures back to JSON — every
serving semantic (batching, coalescing, caching, shedding) lives in the
server. ``ThreadingHTTPServer`` gives one thread per connection, which
is exactly what the micro-batching queue wants: concurrent HTTP clients
land in one collection window and coalesce.

Endpoints:

* ``POST /v1/query`` — body ``{"kind": "factors"|"ic"|"decile"|
  "intraday", "start": int, "end": int, "names"?: [..], "factor"?:
  str, "horizon"?: int, "group_num"?: int}`` -> the answer dict
  (``intraday`` ignores the range and reads the live streaming carry;
  needs a ``stream=True`` server).
  400 on a malformed query, 503 when the server sheds (breaker open /
  queue full) — the HTTP face of backpressure, 500 on a failed dispatch.
* ``POST /v1/ingest`` — body ``{"bars": [[[o,h,l,c,v]×T]×B],
  "present": [[bool×T]×B]}`` advances the streaming carry by ``B``
  minutes; -> ``{"minute", "bars"}``. Same error mapping as query
  (the JSON body bound is wider: a full universe-minute is big).
* ``GET /healthz`` — liveness + breaker state (+ the stream carry's
  minute cursor when streaming is on).
* ``GET /v1/metrics`` — the telemetry registry snapshot (JSON).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from .service import FactorServer, LoadShedError, Query

#: request-body bound (a factors query is a few hundred bytes)
MAX_BODY_BYTES = 1 << 20

#: ingest-body bound: B minutes × T tickers × 5 fields as JSON text
#: (~16 bytes/number puts a 64-minute × 5000-ticker micro-batch well
#: inside 64 MiB)
MAX_INGEST_BODY_BYTES = 64 << 20


def _make_handler(server: FactorServer, timeout: Optional[float]):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            if self.path == "/healthz":
                with server._state_lock:
                    open_until = server._open_until
                    consecutive = server._consecutive
                payload = {
                    "ok": True, "factors": len(server.names),
                    "days": server.source.n_days,
                    "breaker_open": open_until is not None,
                    "breaker_consecutive_failures": consecutive}
                if server.stream_engine is not None:
                    payload["stream_minute"] = \
                        server.stream_engine.minutes
                self._reply(200, payload)
                return
            if self.path == "/v1/metrics":
                self._reply(200, server.telemetry.registry.snapshot())
                return
            self._reply(404, {"error": f"no route {self.path}"})

        def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
            if self.path == "/v1/ingest":
                self._post_ingest()
                return
            if self.path != "/v1/query":
                self._reply(404, {"error": f"no route {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                if length > MAX_BODY_BYTES:
                    self._reply(413, {"error": "body too large"})
                    return
                doc = json.loads(self.rfile.read(length) or b"{}")
                q = Query(
                    kind=doc.get("kind", ""),
                    start=int(doc.get("start", 0)),
                    end=int(doc.get("end", 0)),
                    names=(tuple(doc["names"]) if doc.get("names")
                           else None),
                    factor=doc.get("factor"),
                    horizon=int(doc.get("horizon", 1)),
                    group_num=int(doc.get("group_num", 5)))
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._reply(400, {"error": f"malformed request: {e}"})
                return
            try:
                fut = server.submit(q)
            except LoadShedError as e:
                self._reply(503, {"error": str(e), "shed": True})
                return
            except ValueError as e:
                self._reply(400, {"error": str(e)})
                return
            try:
                self._reply(200, fut.result(timeout))
            except Exception as e:  # noqa: BLE001 — dispatch failure
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        def _post_ingest(self):
            # no numpy here: the JSON lists go to the server verbatim
            # and service.py (the declared GL-A3 boundary module) owns
            # the array conversion + shape validation
            try:
                length = int(self.headers.get("Content-Length", "0"))
                if length > MAX_INGEST_BODY_BYTES:
                    self._reply(413, {"error": "body too large"})
                    return
                doc = json.loads(self.rfile.read(length) or b"{}")
                bars, present = doc["bars"], doc["present"]
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {"error": f"malformed ingest: {e}"})
                return
            try:
                fut = server.ingest(bars, present)
            except LoadShedError as e:
                self._reply(503, {"error": str(e), "shed": True})
                return
            except ValueError as e:
                self._reply(400, {"error": str(e)})
                return
            try:
                self._reply(200, fut.result(timeout))
            except Exception as e:  # noqa: BLE001 — dispatch failure
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

    return Handler


def serve_http(server: FactorServer, host: str = "127.0.0.1",
               port: int = 0, timeout: Optional[float] = 60.0,
               ) -> Tuple[ThreadingHTTPServer, threading.Thread]:
    """Bind ``server`` on ``host:port`` (0 = ephemeral) and serve from a
    daemon thread. Returns ``(httpd, thread)``; the bound port is
    ``httpd.server_address[1]``; stop with ``httpd.shutdown()``."""
    httpd = ThreadingHTTPServer((host, port),
                                _make_handler(server, timeout))
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever, daemon=True,
                              name="factor-serve-http")
    thread.start()
    return httpd, thread
