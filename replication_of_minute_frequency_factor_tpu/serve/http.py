"""Stdlib-only HTTP/JSON binding for :class:`.service.FactorServer`.

Protocol-agnostic by construction: the handler only translates JSON to
:class:`..serve.service.Query` objects and futures back to JSON — every
serving semantic (batching, coalescing, caching, shedding) lives in the
server. ``ThreadingHTTPServer`` gives one thread per connection, which
is exactly what the micro-batching queue wants: concurrent HTTP clients
land in one collection window and coalesce.

Endpoints:

* ``POST /v1/query`` — body ``{"kind": "factors"|"ic"|"decile"|
  "intraday", "start": int, "end": int, "names"?: [..], "factor"?:
  str, "horizon"?: int, "group_num"?: int}`` -> the answer dict
  (``intraday`` ignores the range and reads the live streaming carry;
  needs a ``stream=True`` server).
  400 on a malformed query, 503 when the server sheds (breaker open /
  queue full) — the HTTP face of backpressure, 500 on a failed dispatch.
  Every 503 carries a ``Retry-After`` header (ISSUE 11) derived from
  the breaker cooldown: the remaining cooldown on a breaker shed, the
  full cooldown as the backoff hint on a full-queue shed.
* ``POST /v1/ingest`` — body ``{"bars": [[[o,h,l,c,v]×T]×B],
  "present": [[bool×T]×B]}`` advances the streaming carry by ``B``
  minutes; -> ``{"minute", "bars"}``. Same error mapping as query
  (the JSON body bound is wider: a full universe-minute is big).
* ``POST /v1/discover`` — body ``{"start": int, "end": int,
  "generations"?: int, "pop"?: int, "seed"?: int, "horizon"?: int,
  "skeleton"?: "default"|"rich"}`` runs a bounded-generations
  factor-discovery job on the request queue (ISSUE 14; needs a
  ``research=True`` server) -> the discovery answer (the registered
  ``disc_<hash>`` name, its backtest stats, the persisted record
  path). Same error mapping as query; discovery jobs respect the
  breaker and the bounded queue like any other request.
* ``GET /v1/factors`` — the live factor universe: built-in names plus
  every factor discovered since startup, each immediately queryable
  by name through ``POST /v1/query``.
* ``POST /v1/debug/dump`` — on-demand flight-recorder capture
  (ISSUE 8): dumps the request ring + last-dispatch metadata +
  registry counter deltas; -> ``{"path", "requests"}`` (409 when no
  dump directory is configured anywhere).
* ``GET /healthz`` — liveness: breaker state, uptime, queue depth,
  flight-recorder counts, HBM-stats availability (+ the stream
  carry's minute cursor when streaming is on), and the
  ``factor_health`` data-quality block (ISSUE 12: worst-coverage
  factor, result-wire widen rate, drift bursts) — the same shape the
  fleet front door rolls up per replica.
* ``GET /v1/metrics`` — the telemetry registry: JSON snapshot by
  default; the standard Prometheus text format (v0.0.4) when the
  request asks for it (``Accept: text/plain`` / ``application/
  openmetrics-text``, or ``?format=prometheus``) — scrapeable by
  stock tooling (ISSUE 8).
* ``GET /v1/slo`` — the SLO plane (ISSUE 16): per-objective burn
  rates, budget remaining and alert state as JSON
  (``SloPlane.summary`` + the latest evaluation), or the
  ``slo_*``-only Prometheus view under the same content negotiation
  as ``/v1/metrics`` — for alerting rules that poll the SLO surface
  alone.
* ``GET /v1/timeline?name=&since=`` — the continuous telemetry
  timeline (ISSUE 16): the in-process frame ring, optionally
  filtered to series containing ``name`` and frames at/after unix
  second ``since`` (``limit`` bounds the tail).

Request tracing (ISSUE 8): ``POST /v1/query`` and ``POST /v1/ingest``
accept an ``X-Trace-Id`` header (``[A-Za-z0-9._-]{1,64}``; anything
else is replaced at admission) and every response — success or error —
echoes the request's effective trace ID back in the same header, so a
client can join its own logs to the server's span/request records.

ISSUE 20: this module is now also the serve layer's shared endpoint
LIBRARY — :func:`query_from_doc`, :func:`render_answer` and
:func:`get_payload` are one implementation used by this legacy binding
AND the evented edge (:mod:`.edge`), so the two front doors cannot
drift; ``POST /v1/query`` honors ``Accept: application/x-mff-wire``
(the packed result-wire payload back verbatim, framed) on both.
:func:`serve_frontdoor` binds whichever transport ``ServeConfig.edge``
names.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from ..telemetry.opsplane import canonical_trace_id, to_prometheus
from .service import FactorServer, LoadShedError, Query

#: request-body bound (a factors query is a few hundred bytes)
MAX_BODY_BYTES = 1 << 20

#: ingest-body bound: B minutes × T tickers × 5 fields as JSON text
#: (~16 bytes/number puts a 64-minute × 5000-ticker micro-batch well
#: inside 64 MiB)
MAX_INGEST_BODY_BYTES = 64 << 20


#: the result-wire media type (ISSUE 20): a ``POST /v1/query`` carrying
#: ``Accept: application/x-mff-wire`` gets the packed result-wire
#: payload back VERBATIM, framed by ``data/result_wire.pack_frame`` —
#: both front doors (this module and :mod:`.edge`) honor it through the
#: same :func:`query_from_doc` / :func:`render_answer` pair.
WIRE_CONTENT_TYPE = "application/x-mff-wire"


def retry_after_seconds(retry_after_s: Optional[float]) -> int:
    """``Retry-After`` header value from a shed's backoff hint: whole
    seconds, rounded UP, floor 1 (a zero/None hint must still tell the
    client to back off for a beat, not hammer). Shared by this binding
    and the fleet front door (ISSUE 11) so the two renderings cannot
    drift."""
    import math
    if retry_after_s is None or retry_after_s <= 0:
        return 1
    return max(1, math.ceil(retry_after_s))


def wants_prometheus(accept: str, query: dict) -> bool:
    """The ``/v1/metrics`` & ``/v1/slo`` content negotiation, shared by
    every front door (legacy serve, legacy fleet, edge)."""
    return ("text/plain" in accept or "openmetrics" in accept
            or query.get("format", [""])[0] == "prometheus")


def query_from_doc(doc: dict, accept: str = "") -> Query:
    """One JSON request body -> :class:`Query`, shared by both serve
    front doors and the fleet's (drift between the bindings was the
    pre-ISSUE-20 hazard; now there is one parser). Raises
    ``ValueError``/``TypeError``/``KeyError`` on malformed fields — the
    caller maps those to 400. Wire encoding is negotiated from the
    ``Accept`` header (``application/x-mff-wire``) or an explicit
    ``"encoding": "wire"`` in the body."""
    encoding = ("wire" if (WIRE_CONTENT_TYPE in (accept or "")
                           or doc.get("encoding") == "wire")
                else "json")
    return Query(
        kind=doc.get("kind", ""),
        start=int(doc.get("start", 0)),
        end=int(doc.get("end", 0)),
        names=tuple(doc["names"]) if doc.get("names") else None,
        factor=doc.get("factor"),
        horizon=int(doc.get("horizon", 1)),
        group_num=int(doc.get("group_num", 5)),
        encoding=encoding)


def render_answer(result: dict, q: Query) -> Tuple[str, bytes]:
    """One resolved answer dict -> ``(content_type, body)``. A wire
    answer (``result["wire"]``) frames the packed payload verbatim
    (:func:`..data.result_wire.pack_frame`); everything else is the
    JSON rendering both front doors always produced."""
    if q.encoding == "wire" and result.get("wire"):
        from ..data import result_wire as _rw
        body = _rw.pack_frame(
            result["payload"], n_factors=result["n_factors"],
            days=result["days"], tickers=result["tickers"],
            spill_rows=result["spill_rows"],
            start=result.get("start", 0), end=result.get("end", 0))
        return WIRE_CONTENT_TYPE, body
    return "application/json", json.dumps(result).encode()


def get_payload(server: FactorServer, path: str, query: dict,
                accept: str = "") -> Optional[Tuple[int, str, bytes]]:
    """The GET endpoint surface -> ``(status, content_type, body)``,
    or None for an unknown route. ONE implementation serves both the
    legacy thread-per-connection binding and the evented edge
    (:mod:`.edge`), so the two front doors answer identically by
    construction — the legacy-vs-edge parity tests then verify it."""
    if path == "/healthz":
        return 200, "application/json", \
            json.dumps(server.health()).encode()
    if path == "/v1/factors":
        return 200, "application/json", \
            json.dumps(server.factor_list()).encode()
    if path == "/v1/metrics":
        if wants_prometheus(accept, query):
            return 200, "text/plain; version=0.0.4; charset=utf-8", \
                to_prometheus(server.telemetry.registry).encode()
        return 200, "application/json", \
            json.dumps(server.telemetry.registry.snapshot()).encode()
    if path == "/v1/slo":
        if wants_prometheus(accept, query):
            from ..telemetry.slo import slo_prometheus
            return 200, "text/plain; version=0.0.4; charset=utf-8", \
                slo_prometheus(server.telemetry.registry).encode()
        return 200, "application/json", json.dumps({
            "slo": server.sloplane.summary(),
            "evaluation": server.sloplane.evaluate(),
        }).encode()
    if path == "/v1/timeline":
        try:
            name = query.get("name", [None])[0]
            since_raw = query.get("since", [None])[0]
            since = (float(since_raw) if since_raw is not None
                     else None)
            limit_raw = query.get("limit", [None])[0]
            limit = (int(limit_raw) if limit_raw is not None
                     else None)
        except (TypeError, ValueError) as e:
            return 400, "application/json", json.dumps(
                {"error": f"malformed timeline query: {e}"}).encode()
        frames = server.timeline.query(name=name, since=since,
                                       limit=limit)
        return 200, "application/json", json.dumps(
            {"frames": frames, "count": len(frames)}).encode()
    return None


def _make_handler(server: FactorServer, timeout: Optional[float]):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _reply(self, code: int, payload: dict,
                   trace_id: Optional[str] = None,
                   retry_after_s: Optional[float] = None) -> None:
            self._reply_bytes(code, json.dumps(payload).encode(),
                              "application/json", trace_id,
                              retry_after_s=retry_after_s)

        def _reply_bytes(self, code: int, body: bytes,
                         content_type: str,
                         trace_id: Optional[str] = None,
                         retry_after_s: Optional[float] = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            if trace_id:
                self.send_header("X-Trace-Id", trace_id)
            if retry_after_s is not None:
                self.send_header("Retry-After",
                                 str(retry_after_seconds(retry_after_s)))
            self.end_headers()
            self.wfile.write(body)

        def _trace_id(self) -> str:
            """The request's effective trace ID: the propagated
            ``X-Trace-Id`` when well-formed, else freshly generated —
            the SAME canonicalization the server applies at admission,
            so the echoed header and the recorded ID always agree."""
            return canonical_trace_id(self.headers.get("X-Trace-Id"))

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            # ISSUE 20: the whole GET surface is the shared
            # get_payload builder — the edge serves the same bytes
            parsed = urllib.parse.urlparse(self.path)
            res = get_payload(server, parsed.path,
                              urllib.parse.parse_qs(parsed.query),
                              self.headers.get("Accept", ""))
            if res is None:
                self._reply(404, {"error": f"no route {self.path}"})
                return
            status, ctype, body = res
            self._reply_bytes(status, body, ctype)

        def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
            if self.path == "/v1/ingest":
                self._post_ingest()
                return
            if self.path == "/v1/discover":
                self._post_discover()
                return
            if self.path == "/v1/debug/dump":
                self._post_dump()
                return
            if self.path != "/v1/query":
                self._reply(404, {"error": f"no route {self.path}"})
                return
            tid = self._trace_id()
            try:
                length = int(self.headers.get("Content-Length", "0"))
                if length > MAX_BODY_BYTES:
                    self._reply(413, {"error": "body too large"}, tid)
                    return
                doc = json.loads(self.rfile.read(length) or b"{}")
                q = query_from_doc(doc,
                                   self.headers.get("Accept", ""))
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {"error": f"malformed request: {e}"},
                            tid)
                return
            try:
                fut = server.submit(q, trace_id=tid)
            except LoadShedError as e:
                self._reply(503, {"error": str(e), "shed": True}, tid,
                            retry_after_s=e.retry_after_s)
                return
            except ValueError as e:
                self._reply(400, {"error": str(e)}, tid)
                return
            try:
                ctype, body = render_answer(fut.result(timeout), q)
                self._reply_bytes(200, body, ctype, tid)
            except Exception as e:  # noqa: BLE001 — dispatch failure
                self._reply(500, {"error": f"{type(e).__name__}: {e}"},
                            tid)

        def _post_ingest(self):
            # no numpy here: the JSON lists go to the server verbatim
            # and service.py (the declared GL-A3 boundary module) owns
            # the array conversion + shape validation
            tid = self._trace_id()
            try:
                length = int(self.headers.get("Content-Length", "0"))
                if length > MAX_INGEST_BODY_BYTES:
                    self._reply(413, {"error": "body too large"}, tid)
                    return
                doc = json.loads(self.rfile.read(length) or b"{}")
                bars, present = doc["bars"], doc["present"]
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {"error": f"malformed ingest: {e}"},
                            tid)
                return
            try:
                fut = server.ingest(bars, present, trace_id=tid)
            except LoadShedError as e:
                self._reply(503, {"error": str(e), "shed": True}, tid,
                            retry_after_s=e.retry_after_s)
                return
            except ValueError as e:
                self._reply(400, {"error": str(e)}, tid)
                return
            try:
                self._reply(200, fut.result(timeout), tid)
            except Exception as e:  # noqa: BLE001 — dispatch failure
                self._reply(500, {"error": f"{type(e).__name__}: {e}"},
                            tid)

        def _post_discover(self):
            tid = self._trace_id()
            try:
                length = int(self.headers.get("Content-Length", "0"))
                if length > MAX_BODY_BYTES:
                    self._reply(413, {"error": "body too large"}, tid)
                    return
                doc = json.loads(self.rfile.read(length) or b"{}")
                kwargs = dict(
                    start=int(doc["start"]), end=int(doc["end"]),
                    generations=int(doc.get("generations", 4)),
                    pop=int(doc.get("pop", 128)),
                    seed=int(doc.get("seed", 0)),
                    horizon=int(doc.get("horizon", 1)),
                    skeleton=str(doc.get("skeleton", "default")))
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {"error": f"malformed discover: {e}"},
                            tid)
                return
            try:
                fut = server.discover(trace_id=tid, **kwargs)
            except LoadShedError as e:
                self._reply(503, {"error": str(e), "shed": True}, tid,
                            retry_after_s=e.retry_after_s)
                return
            except ValueError as e:
                self._reply(400, {"error": str(e)}, tid)
                return
            try:
                self._reply(200, fut.result(timeout), tid)
            except Exception as e:  # noqa: BLE001 — dispatch failure
                self._reply(500, {"error": f"{type(e).__name__}: {e}"},
                            tid)

        def _post_dump(self):
            try:
                path = server.debug_dump()
            except Exception as e:  # noqa: BLE001 — dump is best-effort
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                return
            if path is None:
                self._reply(409, {"error": "no flight dump directory "
                                           "configured "
                                           "(ServeConfig.flight_dir)"})
                return
            self._reply(200, {"path": path,
                              "requests": len(server.flight)})

    return Handler


def serve_http(server: FactorServer, host: str = "127.0.0.1",
               port: int = 0, timeout: Optional[float] = 60.0,
               ) -> Tuple[ThreadingHTTPServer, threading.Thread]:
    """Bind ``server`` on ``host:port`` (0 = ephemeral) and serve from a
    daemon thread. Returns ``(httpd, thread)``; the bound port is
    ``httpd.server_address[1]``; stop with ``httpd.shutdown()``."""
    httpd = ThreadingHTTPServer((host, port),
                                _make_handler(server, timeout))
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever, daemon=True,
                              name="factor-serve-http")
    thread.start()
    return httpd, thread


def serve_frontdoor(server: FactorServer, host: str = "127.0.0.1",
                    port: int = 0, timeout: Optional[float] = 60.0,
                    transport: Optional[str] = None):
    """Bind the CONFIGURED front door (ISSUE 20): ``transport`` (or
    ``ServeConfig.edge`` when None) picks the evented selectors loop
    (``'edge'``, :mod:`.edge`) or this module's stdlib
    thread-per-connection server (``'legacy'`` — the A/B and fallback
    path). Returns an object with ``.server_address`` and
    ``.shutdown()`` either way, so callers stop caring which one
    runs."""
    transport = transport or server.scfg.edge
    if transport == "legacy":
        httpd, _thread = serve_http(server, host=host, port=port,
                                    timeout=timeout)
        return httpd
    if transport != "edge":
        raise ValueError(f"unknown front-door transport {transport!r} "
                         "(edge or legacy)")
    from .edge import serve_edge
    return serve_edge(server, host=host, port=port, timeout=timeout)
