"""Device-facing serve compute: block builds + query graphs, all AOT.

One *block* is everything the service needs to answer any query over a
day-range: the stacked ``[F, D, T]`` exposures of the server's factor
set plus the per-(day, ticker) daily close and validity planes the IC
and decile queries derive forward returns from. A block is built by ONE
fused executable (wire unpack + decode + all factors + close extraction
in a single XLA module — the same single-dispatch shape as
``pipeline._compute_packed``) and stays on device; the service's
exposure cache owns its lifetime.

Every device entry point here dispatches through the
:class:`..serve.executables.ExecutableCache`, so a warm server compiles
NOTHING on a repeat request shape — asserted by the serving tests via
the ``xla.compiles`` registry counter, not by reading this docstring.

This module is device-hot (graftlint GL-A3 scope): results leave as
device arrays; the request loop in :mod:`.service` is the boundary
module that materializes them.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data import result_wire
from ..data import wire
from ..eval_ops import _qcut_labels_jit, ic_series
from ..models.registry import compute_factors
from ..telemetry.factorplane import factor_stats_block
from .executables import ExecutableCache


def _block_fn(buf, spec, kind, names, replicate_quirks, rolling_impl,
              session=None):
    """The fused block graph: one packed uint8 buffer in, the whole
    query-answering state out. ``close`` is each (day, ticker)'s last
    valid bar's close (NaN when the day has no valid bar) — the basis
    for the forward returns IC/decile queries correlate against.
    ``stats`` (ISSUE 12) is the per-factor data-quality sketch fused
    as a side-output of the SAME module — the request loop feeds it to
    the factor-health plane at the block-build boundary, zero extra
    dispatches."""
    arrs = wire.unpack(buf, spec)
    if kind == "wire":
        bars, m = wire.decode(*arrs)
    else:
        bars, m = arrs
        m = m.astype(bool)
    out = compute_factors(bars, m, names=names,
                          replicate_quirks=replicate_quirks,
                          rolling_impl=rolling_impl, session=session)
    exposures = jnp.stack([out[n] for n in names])  # [F, D, T]
    slots = jnp.arange(m.shape[-1])
    last = jnp.max(jnp.where(m, slots, -1), axis=-1)  # [D, T]
    valid = last >= 0
    close = jnp.take_along_axis(
        bars[..., 3], jnp.maximum(last, 0)[..., None], axis=-1)[..., 0]
    close = jnp.where(valid, close, jnp.nan)
    return exposures, close, valid, factor_stats_block(exposures)


_BLOCK_STATIC = ("spec", "kind", "names", "replicate_quirks",
                 "rolling_impl", "session")
_block_jit = functools.partial(jax.jit,
                               static_argnames=_BLOCK_STATIC)(_block_fn)


def _fwd_returns(close, valid, horizon: int):
    """``ret[d] = close[d+h]/close[d] - 1`` with the last ``h`` days
    invalid (no forward close inside the block)."""
    pad_c = jnp.full((horizon,) + close.shape[1:], jnp.nan, close.dtype)
    pad_v = jnp.zeros((horizon,) + valid.shape[1:], bool)
    fwd_close = jnp.concatenate([close[horizon:], pad_c])
    fwd_ok = jnp.concatenate([valid[horizon:], pad_v])
    ret = fwd_close / close - 1.0
    return ret, fwd_ok & valid


def _ic_fn(exposures, close, valid, row, horizon):
    """Per-date Pearson IC + Spearman rank-IC of factor ``row`` against
    ``horizon``-day forward close returns, inside the block."""
    exp = exposures[row]
    ret, ok = _fwd_returns(close, valid, horizon)
    v = ok & jnp.isfinite(exp) & jnp.isfinite(ret)
    return ic_series(jnp.where(v, exp, 0.0), jnp.where(v, ret, 0.0), v)


_ic_jit = functools.partial(
    jax.jit, static_argnames=("row", "horizon"))(_ic_fn)

#: result-wire encode of a block's stacked exposures (ISSUE 10): the
#: answer leg's device half. Encodes from the cache's RAW f32 block
#: every time — the cache never holds quantized data, so repeated
#: answers can never re-quantize a decode (no double quantization by
#: construction), and the encode is deterministic on the same block.
_encode_exposures_jit = functools.partial(
    jax.jit, static_argnames=("result_spec",))(
        lambda exposures, result_spec:
        result_wire.encode_block(exposures, result_spec))


def _decile_fn(exposures, close, valid, row, horizon, group_num):
    """Per-date quantile buckets of factor ``row`` (polars-qcut
    semantics via eval_ops) with per-bucket counts and mean forward
    returns."""
    exp = exposures[row]
    v = valid & jnp.isfinite(exp)
    labels = _qcut_labels_jit(exp, v, group_num)  # [D, T], -1 invalid
    ret, ok = _fwd_returns(close, valid, horizon)
    onehot = labels[..., None] == jnp.arange(group_num)  # [D, T, G]
    counts = jnp.sum(onehot & v[..., None], axis=1)
    okr = onehot & (ok & jnp.isfinite(ret) & v)[..., None]
    n_ret = jnp.sum(okr, axis=1)
    ret_sum = jnp.sum(jnp.where(okr, ret[..., None], 0.0), axis=1)
    mean_ret = jnp.where(n_ret > 0, ret_sum / n_ret, jnp.nan)
    return labels, counts, mean_ret


_decile_jit = functools.partial(
    jax.jit, static_argnames=("row", "horizon", "group_num"))(_decile_fn)


class ServeEngine:
    """Builds and queries blocks for one server's factor set.

    Holds the widen-only wire ``floor`` across blocks (so same-extent
    day-ranges converge on one spec — and therefore ONE compiled block
    executable) and the :class:`ExecutableCache` all dispatches go
    through.
    """

    def __init__(self, names: Sequence[str], replicate_quirks: bool = True,
                 rolling_impl: Optional[str] = None, telemetry=None,
                 executables: Optional[ExecutableCache] = None,
                 session=None):
        from ..config import get_config
        from ..markets import get_session
        #: the source's market session (ISSUE 15): the block graph and
        #: every query trace over its slot grid; None = cn_ashare_240
        self.session = get_session(session)
        self.names: Tuple[str, ...] = tuple(names)
        self.replicate_quirks = replicate_quirks
        self.rolling_impl = (rolling_impl if rolling_impl is not None
                             else get_config().rolling_impl)
        self.telemetry = telemetry
        self.executables = (executables if executables is not None
                            else ExecutableCache(telemetry=telemetry))
        self._floor: dict = {}

    def _tel(self):
        if self.telemetry is not None:
            return self.telemetry
        from ..telemetry import get_telemetry
        return get_telemetry()

    # --- block build ----------------------------------------------------
    def build_block(self, bars: np.ndarray,
                    mask: np.ndarray) -> Dict[str, object]:
        """Encode + transfer + one fused dispatch; returns the block as
        DEVICE arrays ``{exposures, close, valid}``. The result is
        dispatched asynchronously — errors surface when the service
        materializes an answer from it."""
        w = wire.encode(bars, mask, floor=self._floor)
        if w is not None:
            buf, spec = wire.pack_arrays(w.arrays)
            kind = "wire"
        else:
            buf, spec = wire.pack_arrays((bars, mask.view(np.uint8)))
            kind = "raw"
        dbuf = jax.device_put(buf)
        key = ("block", len(buf), spec, kind, self.names,
               self.replicate_quirks, self.rolling_impl,
               self.session.name)
        compiled = self.executables.get(
            "serve_block", key,
            lambda: _block_jit.lower(dbuf, spec, kind, self.names,
                                     self.replicate_quirks,
                                     self.rolling_impl, self.session))
        exposures, close, valid, stats = compiled(dbuf)
        block = {"exposures": exposures, "close": close, "valid": valid,
                 "stats": stats}
        # device bytes this block pins (shape metadata, not a sync):
        # the HBM signal the exposure-cache LRU budget is set against
        self._tel().gauge("serve.block_bytes", sum(
            int(getattr(v, "nbytes", 0) or 0) for v in block.values()))
        return block

    # --- queries (device in, device out) --------------------------------
    def row(self, name: str) -> int:
        return self.names.index(name)

    def ic(self, block: Dict[str, object], name: str, horizon: int):
        """Device ``(ic [D], rank_ic [D])`` for one factor."""
        exposures = block["exposures"]
        row = self.row(name)
        key = ("ic", exposures.shape, row, horizon)
        compiled = self.executables.get(
            "serve_ic", key,
            lambda: _ic_jit.lower(exposures, block["close"],
                                  block["valid"], row, horizon))
        return compiled(exposures, block["close"], block["valid"])

    def result_spec(self, days: int) -> "result_wire.ResultWireSpec":
        """The server's static result-wire spec for a ``days``-deep
        block (pinned per-factor bounds + the default spill budget)."""
        return result_wire.ResultWireSpec.for_names(self.names,
                                                    days=days)

    def encode_exposures(self, block: Dict[str, object]):
        """Result-wire encode of the block's ``[F, D, T]`` exposures as
        ONE warm device dispatch -> packed ``[L] uint8`` payload (still
        on device; the request loop fetches + host-dequantizes it).
        Always encodes from the cached RAW f32 exposures — see
        ``_encode_exposures_jit`` for the no-double-quantization
        argument."""
        exposures = block["exposures"]
        spec = self.result_spec(int(exposures.shape[1]))
        key = ("result_encode", exposures.shape, spec)
        compiled = self.executables.get(
            "serve_result_encode", key,
            lambda: _encode_exposures_jit.lower(exposures, spec))
        return compiled(exposures), spec

    def decile(self, block: Dict[str, object], name: str, horizon: int,
               group_num: int):
        """Device ``(labels [D, T], counts [D, G], mean_fwd_ret
        [D, G])`` for one factor."""
        exposures = block["exposures"]
        row = self.row(name)
        key = ("decile", exposures.shape, row, horizon, group_num)
        compiled = self.executables.get(
            "serve_decile", key,
            lambda: _decile_jit.lower(exposures, block["close"],
                                      block["valid"], row, horizon,
                                      group_num))
        return compiled(exposures, block["close"], block["valid"])
