"""``MinFreqFactor`` — the minute-factor pipeline class (L2 user API).

Mirrors the reference's ``MinFreqFactor(Factor)``
(MinuteFrequentFactorCICC.py:8-245): exposure-cache resolution
(``_read_exposure``, :27-48), the batch/incremental compute entry point
(``cal_exposure_by_min_data``, :50-112) and the final-exposure resampler
(``cal_final_exposure``, :114-245). The compute driver delegates to
:mod:`.pipeline` — all requested factors in one fused XLA graph per day
batch instead of one polars pass per factor per process.
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Union

import numpy as np

from . import frames
from .config import Config, get_config
from .factor import Factor
from .models.registry import factor_names, register_alias
from .pipeline import compute_exposures

AGG_METHODS = ("o", "m", "z", "std")


class MinFreqFactor(Factor):
    """One minute-frequency factor: compute, cache, resample, evaluate."""

    def __init__(self, factor_name: str, factor_exposure=None):
        super().__init__(factor_name, factor_exposure)

    # ------------------------------------------------------------------
    # cache resolution (reference :27-48)
    # ------------------------------------------------------------------
    def _read_exposure(self, path: Optional[str] = None, default=None):
        """Load a cached exposure. ``path`` may be the parquet file itself
        or a directory containing ``<factor_name>.parquet``; returns
        ``default`` when no cache exists (the caller then computes from
        scratch) — the reference's third positional argument (:27-48)."""
        path = self._resolve_path(path)
        if not os.path.exists(path):
            return default
        self.read_parquet(path)
        return self.factor_exposure

    # ------------------------------------------------------------------
    # batch/incremental compute (reference :50-112)
    # ------------------------------------------------------------------
    def cal_exposure_by_min_data(
        self,
        calculate_method: Union[str, Callable, None] = None,
        path: Optional[str] = None,
        n_jobs: Optional[int] = None,
        minute_dir: Optional[str] = None,
        cfg: Optional[Config] = None,
        progress: bool = True,
        fault_hook=None,
        retry_failed: bool = False,
    ) -> "MinFreqFactor":
        """Compute this factor for every day file, resuming incrementally.

        The resume rule is the reference's: only day files NEWER than the
        cached max date recompute, so a day that failed mid-run while
        later days completed is never retried by a plain rerun — pass
        ``retry_failed=True`` to also recompute the days recorded in
        ``<cache>.failures.json``.

        ``calculate_method`` is a registered kernel name (defaults to
        ``factor_name``) or an ad-hoc kernel ``fn(ctx) -> [..., T]`` —
        the reference passed the ``cal_xxx`` function object here
        (MinuteFrequentFactorCICC.py:50); names are the jit-friendly
        equivalent. The exposure cache at ``path`` follows the reference's
        contract: only day files newer than the cached max date recompute.

        ``n_jobs`` (the reference's joblib process count, :54) is accepted
        for drop-in compatibility and ignored: there is no process pool —
        days batch through one fused device graph.
        """
        del n_jobs
        cfg = cfg or get_config()
        name = self.factor_name
        if calculate_method is not None:
            if isinstance(calculate_method, str) \
                    and calculate_method not in factor_names():
                raise KeyError(
                    f"unknown factor kernel {calculate_method!r}")
            # expose the kernel under this factor's name so the cache column
            # carries factor_name (reference cached <factor_name>.parquet
            # whatever cal_* method produced it)
            register_alias(name, calculate_method)
        elif name not in factor_names():
            raise KeyError(
                f"{name!r} is not a registered kernel; pass "
                f"calculate_method= (one of {len(factor_names())} names)")

        cache_path = self._resolve_path(path)
        table = compute_exposures(
            minute_dir=minute_dir, names=(name,), cache_path=cache_path,
            cfg=cfg, progress=progress, fault_hook=fault_hook,
            retry_failed=retry_failed)
        self.failures = getattr(table, "failures", None)
        self.set_exposure(table.columns["code"], table.columns["date"],
                          table.columns[name])
        return self

    # ------------------------------------------------------------------
    # final-exposure resampling (reference :114-245)
    # ------------------------------------------------------------------
    def cal_final_exposure(
        self,
        frequency: Union[str, int] = "week",
        method: str = "o",
        mode: str = "calendar",
        stock_pool: str = "full",
        pool: Optional[str] = None,
    ) -> "MinFreqFactor":
        """Resample the daily exposure along the date axis, per code.

        ``mode='calendar'``: calendar buckets (week/month/quarter/year) with
        aggregation ``method`` — 'o' last, 'm' mean, 'z' (last-mean)/std,
        'std' — output named ``{frequency}_{name}_{method}``
        (reference :130-186, column naming :141).

        ``mode='days'``: rolling ``frequency``-day window over each code's
        own trading days, ``min_samples = frequency``; 'z' and 'std' use
        population std (ddof=0, reference :222,234); output named
        ``{name}_{t}_{method}`` (:189).

        ``stock_pool``: the reference advertises index pools (hs300/
        zz500/zz1000) but raises for anything except ``'full'`` (quirk
        Q9, MinuteFrequentFactorCICC.py:137-140). Here a non-'full' pool
        works when ``Config.stock_pool_path`` names a membership parquet
        (exact member-days or CSMAR in/out-date intervals — see
        ``data.io.read_stock_pool``): exposure rows outside the pool are
        dropped before resampling. Without a configured membership file
        the reference's error is kept.
        """
        if pool is not None:  # the reference's spelling of stock_pool
            stock_pool = pool
        if method not in AGG_METHODS:
            raise ValueError(f"method must be one of {AGG_METHODS}")
        exp = self._require_exposure()
        code, date = exp["code"], exp["date"]
        val = np.asarray(exp[self.factor_name], np.float64)

        if stock_pool != "full":
            pool_path = get_config().stock_pool_path
            if pool_path is None:
                raise ValueError(
                    "stock_pool={!r} needs Config.stock_pool_path (a "
                    "membership parquet); without one only 'full' exists "
                    "— the reference itself raises here (quirk Q9, "
                    "MinuteFrequentFactorCICC.py:137-140)".format(stock_pool))
            from .data import io as dio
            pc, pd_ = dio.read_stock_pool(pool_path, stock_pool,
                                          np.unique(date))
            sel = dio.membership_filter(code, date, pc, pd_)
            code, date, val = code[sel], date[sel], val[sel]

        if mode == "calendar":
            period = frames.period_start(date, frequency)
            order, seg, n = frames.group_segments(code, period)
            v = val[order]
            nanv = ~np.isfinite(v)
            cnt = np.zeros(n)
            s = np.zeros(n)
            ss = np.zeros(n)
            np.add.at(cnt, seg[~nanv], 1.0)
            np.add.at(s, seg[~nanv], v[~nanv])
            np.add.at(ss, seg[~nanv], v[~nanv] ** 2)
            with np.errstate(invalid="ignore", divide="ignore"):
                mean = s / cnt
                std1 = np.sqrt(np.maximum(ss - cnt * mean**2, 0.0)
                               / (cnt - 1))
            # exactly-constant groups: sum-of-squares rounding can leave a
            # tiny nonzero std (turning the z-score's 0/0 into garbage);
            # segment min==max detects them exactly. cnt==1 keeps its NaN
            # std (ddof=1), matching polars' null.
            smin = np.full(n, np.inf)
            smax = np.full(n, -np.inf)
            np.minimum.at(smin, seg[~nanv], v[~nanv])
            np.maximum.at(smax, seg[~nanv], v[~nanv])
            const_s = (cnt > 0) & (smin == smax)
            mean = np.where(const_s, smin, mean)
            std1 = np.where(const_s & (cnt > 1), 0.0, std1)
            # 'last' skips NaN like polars .last() skips... (polars last()
            # returns the literal last element; NaN rows were never written
            # by the pipeline as nulls — keep literal last)
            last = frames.segment_last(v, seg, n)
            with np.errstate(invalid="ignore", divide="ignore"):
                if method == "o":
                    out = last
                elif method == "m":
                    out = mean
                elif method == "z":
                    out = (last - mean) / std1  # 0/0 (constant) -> NaN
                else:
                    out = std1
            out_code = frames.segment_last(np.asarray(code, object)[order],
                                           seg, n)
            out_date = frames.segment_last(period[order], seg, n)
            new_name = f"{frequency}_{self.factor_name}_{method}"
        elif mode == "days":
            t = int(frequency)
            if t < 1:
                raise ValueError(f"rolling window must be >= 1 day, got {t}")
            if method == "o":
                # pure passthrough rename — NO rolling window and NO
                # min_samples mask (MinuteFrequentFactorCICC.py:190-198,
                # verified by tools/refdiff compare_final_exposure); skip
                # the window machinery entirely
                out, out_code, out_date = val.copy(), code, date
                new_name = f"{self.factor_name}_{t}_{method}"
                return self._finish_final_exposure(out_code, out_date,
                                                   out, new_name)
            order = np.lexsort((date, code))
            c, v = np.asarray(code, object)[order], val[order]
            grp_start = np.r_[True, c[1:] != c[:-1]]
            gid = np.cumsum(grp_start) - 1
            first_of_group = np.flatnonzero(grp_start)[gid]
            idx = np.arange(len(v))
            pos = idx - first_of_group  # row index within the code group
            nanv = ~np.isfinite(v)
            cs = np.r_[0.0, np.cumsum(np.where(nanv, 0.0, v))]
            css = np.r_[0.0, np.cumsum(np.where(nanv, 0.0, v * v))]
            cb = np.r_[0, np.cumsum(nanv)]
            lo = idx - t + 1
            ok = (pos >= t - 1)
            lo_c = np.maximum(lo, 0)
            wsum = cs[idx + 1] - cs[lo_c]
            wss = css[idx + 1] - css[lo_c]
            wbad = (cb[idx + 1] - cb[lo_c]) > 0
            with np.errstate(invalid="ignore", divide="ignore"):
                mean = wsum / t
                var0 = np.maximum(wss / t - mean**2, 0.0)  # ddof=0 (:222,234)
                std0 = np.sqrt(var0)
            # Exactly-constant windows (every window when t == 1):
            # prefix-sum differencing cannot represent their zero variance
            # — cs rounding leaves std0 tiny-nonzero or mean != v, turning
            # the z-score's 0/0 into garbage. A window ending at idx is
            # constant iff the run of adjacent-equal non-NaN values ending
            # there spans it (O(n), vs O(n*t) windowed min/max); its mean
            # is then the row's own value exactly. Windows crossing code
            # groups or containing NaN are masked by ok/wbad below, so a
            # run continuing across a group boundary never ships.
            eq = np.zeros(len(v), bool)
            if len(v) > 1:
                eq[1:] = ~nanv[1:] & ~nanv[:-1] & (v[1:] == v[:-1])
            run = idx - np.maximum.accumulate(np.where(~eq, idx, 0))
            const_w = (run >= t - 1) & ~nanv
            mean = np.where(const_w, v, mean)  # const_w excludes NaN rows
            std0 = np.where(const_w, 0.0, std0)
            with np.errstate(invalid="ignore", divide="ignore"):
                if method == "m":
                    res = mean
                elif method == "z":
                    res = (v - mean) / std0
                else:
                    res = std0
            res = np.where(ok & ~wbad, res, np.nan)
            out = np.empty_like(res)
            out[order] = res
            out_code, out_date = code, date
            new_name = f"{self.factor_name}_{t}_{method}"
        else:
            raise ValueError(f"mode must be 'calendar' or 'days', got {mode!r}")

        return self._finish_final_exposure(out_code, out_date, out,
                                           new_name)

    @staticmethod
    def _finish_final_exposure(out_code, out_date, out, new_name):
        result = MinFreqFactor(new_name)
        result.set_exposure(out_code, np.asarray(out_date, "datetime64[D]"),
                            np.asarray(out, np.float32))
        # sorted (date, code) like every exposure (SURVEY.md §2.3)
        o = np.lexsort((result.factor_exposure["code"],
                        result.factor_exposure["date"]))
        result.factor_exposure = {k: np.asarray(vv)[o]
                                  for k, vv in result.factor_exposure.items()}
        return result
