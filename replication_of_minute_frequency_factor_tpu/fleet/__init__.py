"""fleet/ — N FactorServer replicas as ONE pod (ISSUE 11).

``serve/`` made the pipeline a resident process; this package
multiplies it. Every ingredient already existed — the AOT executable
cache, the device-resident exposure cache, the coalescing micro-batch
queue + breaker (PR 6), streaming ingest (PR 7), the flight recorder /
HBM watermarks / Prometheus scrape (PR 8), and the schema-v3 multihost
bundle aggregation (PR 9) — the fleet composes them:

* :mod:`.replica` — :func:`partition_devices` (disjoint per-replica
  device submeshes) + :class:`Replica`: one FactorServer pinned to its
  submesh with its own Telemetry, identity-stamped bundles
  (``process_index``/``host``), and the device-liveness probe;
* :mod:`.router` — :class:`FleetRouter`: bounded pod admission +
  **coalescing-aware affinity** (rendezvous hash on the query's
  ``(start, end)`` range, so same-range queries still collapse to one
  dispatch on one replica), ingest fan-out with per-replica failure
  isolation, trace-ID propagation through the hop;
  :class:`FactorFleet` composes replicas + policy + router;
* :mod:`.policy` — :class:`ShedPolicy`: demote/probe/restore driven by
  the existing breaker + HBM headroom signals; pod-level shed (503 +
  ``Retry-After``) only when every candidate is out;
* :mod:`.http` — the one front door (``/v1/query``, ``/v1/ingest``,
  ``/healthz`` per-replica + rollup, ``/v1/metrics`` as the
  registry-merge pod fold), HTTP-compatible with a single server; the
  evented edge binding rides the same shared payload builders
  (``serve_fleet_frontdoor`` picks edge vs legacy by
  ``FleetConfig.edge``; ISSUE 20).

Run it: ``python -m replication_of_minute_frequency_factor_tpu serve
--fleet N`` (docs/fleet.md); load-bench it: ``python bench.py fleet``
(the declared ``r11_fleet_v1`` methodology).
"""

from __future__ import annotations

from .http import (FleetEdgeBackend, fleet_get_payload, pod_registry,
                   serve_fleet_edge, serve_fleet_frontdoor,
                   serve_fleet_http)
from .policy import ShedPolicy
from .replica import Replica, build_replicas, partition_devices
from .router import FactorFleet, FleetConfig, FleetRouter, FleetShedError

__all__ = [
    "FactorFleet", "FleetConfig", "FleetRouter", "FleetShedError",
    "Replica", "ShedPolicy", "build_replicas", "partition_devices",
    "FleetEdgeBackend", "fleet_get_payload", "pod_registry",
    "serve_fleet_edge", "serve_fleet_frontdoor", "serve_fleet_http",
]
