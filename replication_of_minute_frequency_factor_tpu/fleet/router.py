"""The thin router: one pod surface over N replicas.

Routing is **coalescing-aware affinity** (ISSUE 11): the routing key is
the query's ``(start, end)`` day-range — the SAME key the replica's
micro-batch queue coalesces on — placed by rendezvous (highest-random-
weight) hashing over the current candidates. Same-range concurrent
queries therefore land on the same replica and still collapse to ONE
device dispatch in its queue, and each range's block executable +
exposure cache entry exists on exactly one replica (compile/cache
locality for free). Intraday queries share one ``intraday`` key; a
demotion only remaps the keys the lost replica owned.

Admission is bounded twice: a pod-level in-flight gate here (a router
in front of N bounded queues must not become the unbounded one), then
each replica's own queue/breaker. A replica-level shed reroutes to the
next candidate with the shed replica excluded; a pod with no candidates
sheds with ``Retry-After`` (:class:`FleetShedError`).

Ingest fan-out: :meth:`FleetRouter.ingest` broadcasts one minute-bar
micro-batch to every live stream replica with per-replica failure
isolation — a failed leg fails (and is surfaced) alone, later fan-outs
exclude the demoted replica until the policy re-probes it, and the pod
keeps serving intraday from the healthy carries (docs/fleet.md spells
out the re-sync contract for a recovered replica's carry).

Trace IDs propagate through the hop: the router canonicalizes at pod
admission, records its own ``route`` request record (replica + key),
and hands the SAME ID to the replica — one request is reconstructable
router→replica across the two telemetry streams.

Answer encoding propagates the same way (ISSUE 20): the router hands
the :class:`Query` to the owning replica VERBATIM, so a wire-encoded
query answers with the replica's packed result-wire payload and the
router hop never re-inflates it to JSON (``fleet.routed_wire`` counts
those; docs/fleet.md "Router-leg encoding").

graftlint note (docs/static-analysis.md): this module is a declared
GL-A3 boundary module of the ``fleet/`` layer — its one allowed host
sync is the ``np.asarray`` that normalizes an ingest body ONCE before
the fan-out (N replicas then share one buffer instead of each paying
the conversion).
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..serve.service import LoadShedError, Query
from ..telemetry.opsplane import canonical_trace_id
from .policy import ShedPolicy
from .replica import Replica, build_replicas


class FleetShedError(LoadShedError):
    """Pod-level shed: every routing candidate is out (demoted, queue
    full, breaker open). Carries the ``Retry-After`` hint like every
    other shed."""


@dataclasses.dataclass
class FleetConfig:
    """Pod knobs (per-replica knobs stay on ``ServeConfig``)."""
    #: pod-level in-flight bound across all replicas; past it the
    #: router sheds before touching any replica queue
    admission_limit: int = 4096
    #: seconds a demoted replica drains before the half-open probe
    demote_cooldown_s: float = 1.0
    #: demote when a replica's measured device bytes exceed
    #: ``cache_bytes * hbm_headroom_frac`` (estimates never demote)
    hbm_headroom_frac: float = 1.5
    #: Retry-After fallback when no demotion cooldown is pending
    retry_after_default_s: float = 1.0
    #: routing keys remembered for the affinity hit-rate counter
    affinity_memory: int = 4096
    #: where POD-level flight dumps (``slo_burn`` on a pod objective)
    #: land (ISSUE 16; None = counters only). Replica anomaly dumps
    #: keep landing in each replica's own ``ServeConfig.flight_dir``.
    flight_dir: Optional[str] = None
    #: pod timeline sampler period (ISSUE 16; 0 disables). Samples
    #: the CONTROL-PLANE registry (router/policy counters) plus
    #: derived per-replica signals — never the per-replica registry
    #: merge, which is scrape-time work (``/v1/metrics``)
    timeline_sample_period_s: float = 0.5
    #: divides the SLO burn windows (telemetry/slo.BURN_WINDOWS)
    slo_time_scale: float = 1.0
    #: pod freshness objective threshold (s) on the worst live
    #: replica's ingest staleness
    slo_staleness_s: float = 120.0
    #: pod front-door transport (ISSUE 20): ``'edge'`` = the evented
    #: selectors loop (:func:`.http.serve_fleet_edge`), ``'legacy'`` =
    #: stdlib thread-per-connection (the A/B and fallback path)
    edge: str = "edge"
    #: per-tenant token-bucket rate on the edge (requests/s; 0 = off),
    #: layered ABOVE pod admission — same contract as
    #: ``ServeConfig.tenant_quota_rps``
    tenant_quota_rps: float = 0.0
    #: bucket depth (0 -> ``max(1, tenant_quota_rps)``)
    tenant_quota_burst: float = 0.0
    #: edge idle-connection reap bound (s; the slow-loris bound)
    edge_idle_timeout_s: float = 30.0


def _rendezvous_order(labels: Sequence[str], key: Tuple) -> List[str]:
    """Labels by descending rendezvous weight for ``key`` — a stable
    hash (not Python's seeded one), so the owner of a range survives
    process restarts and is test-assertable."""
    token = repr(key).encode()

    def score(label: str) -> int:
        h = hashlib.blake2b(label.encode() + b"|" + token,
                            digest_size=8)
        return int.from_bytes(h.digest(), "big")

    return sorted(labels, key=score, reverse=True)


#: graftlint Tier C concurrency contract (analysis/concurrency_tier.py;
#: runtime twin telemetry/lockcheck.py): the admission count and the
#: affinity memo are hit by every concurrently-routed request.
GLC_CONTRACT = {
    "FleetRouter": {
        "lock": "_lock",
        "guards": ("_inflight", "_route_memo"),
        "init": (),
        "locked": (),
    },
}


class FleetRouter:
    """Routes queries/ingests over the policy's current candidates."""

    def __init__(self, replicas: Sequence[Replica],
                 policy: ShedPolicy, telemetry=None,
                 cfg: Optional[FleetConfig] = None):
        from ..telemetry import get_telemetry
        self.replicas = list(replicas)
        self.policy = policy
        self.cfg = cfg or FleetConfig()
        self.telemetry = (telemetry if telemetry is not None
                          else get_telemetry())
        self._by_label = {r.label: r for r in self.replicas}
        self._lock = threading.Lock()
        self._inflight = 0
        #: routing key -> last owning label (bounded): the affinity
        #: hit-rate's memory, not the routing truth (rendezvous is)
        self._route_memo: Dict[Tuple, str] = {}
        from ..telemetry.lockcheck import maybe_install
        maybe_install(self)

    def inflight(self) -> int:
        """Locked read of the admission count — the health rollup's
        accessor (GL-C1: cross-object reads of guarded state go
        through the owner's lock)."""
        with self._lock:
            return self._inflight

    # --- routing --------------------------------------------------------
    def routing_key(self, q: Query) -> Tuple:
        return (("intraday",) if q.kind == "intraday"
                else (q.start, q.end))

    def route_order(self, key: Tuple,
                    candidates: Optional[Sequence[Replica]] = None
                    ) -> List[Replica]:
        """Candidates in rendezvous preference order for ``key`` (the
        first is the key's owner while it stays live)."""
        if candidates is None:
            candidates = self.policy.candidates()
        by_label = {r.label: r for r in candidates}
        return [by_label[l_] for l_
                in _rendezvous_order(sorted(by_label), key)]

    def _admit(self) -> None:
        with self._lock:
            if self._inflight >= self.cfg.admission_limit:
                self.telemetry.counter("fleet.load_shed",
                                       reason="admission")
                raise FleetShedError(
                    f"pod admission queue full "
                    f"({self.cfg.admission_limit} in flight)",
                    retry_after_s=self.cfg.retry_after_default_s)
            self._inflight += 1

    def _release(self) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            inflight = self._inflight
        self.telemetry.gauge("fleet.inflight", inflight)

    def _note_affinity(self, key: Tuple, label: str) -> None:
        with self._lock:
            prev = self._route_memo.get(key)
            if len(self._route_memo) >= self.cfg.affinity_memory \
                    and key not in self._route_memo:
                self._route_memo.clear()  # bounded, coarse reset
            self._route_memo[key] = label
        if prev is not None:
            self.telemetry.counter(
                "fleet.affinity",
                outcome="hit" if prev == label else "miss")

    def submit(self, q: Query, trace_id: Optional[str] = None):
        """Route one query; returns the owning replica's Future. The
        answer dict carries the pod-assigned trace ID back. Sheds with
        :class:`FleetShedError` when no candidate admits it."""
        tid = canonical_trace_id(trace_id)
        key = self.routing_key(q)
        self._admit()
        t0 = time.perf_counter()
        try:
            candidates = self.policy.candidates()
            if not candidates:
                self.telemetry.counter("fleet.load_shed",
                                       reason="no_candidates")
                raise FleetShedError(
                    "every replica is out of routing candidacy "
                    "(demoted/draining); pod is shedding",
                    retry_after_s=self.policy.retry_after_s(
                        self.cfg.retry_after_default_s))
            last_shed: Optional[LoadShedError] = None
            for replica in self.route_order(key, candidates):
                label = replica.label
                try:
                    fut = replica.server.submit(q, trace_id=tid)
                except LoadShedError as e:
                    # replica-level shed: exclude it, try the next
                    # candidate; its breaker/queue state reaches the
                    # policy on the next refresh
                    last_shed = e
                    self.telemetry.counter("fleet.reroutes",
                                           replica=label)
                    self.policy.note_result(label, ok=False)
                    continue
                self._note_affinity(key, label)
                self.telemetry.counter("fleet.routed", replica=label)
                if q.encoding == "wire":
                    # ISSUE 20: the replica leg carries the query's
                    # encoding verbatim — a wire query routed here
                    # answers with the packed payload, never a JSON
                    # re-inflation at the router hop
                    self.telemetry.counter("fleet.routed_wire",
                                           replica=label)
                self.telemetry.request({
                    "trace_id": tid, "op": "route", "status": "ok",
                    "data": {"replica": label, "kind": q.kind,
                             "key": list(key),
                             "route_s": round(time.perf_counter() - t0,
                                              6)}})
                policy = self.policy

                def _done(f, _label=label):
                    self._release()
                    policy.note_result(_label,
                                       ok=f.exception() is None)

                fut.add_done_callback(_done)
                return fut
            self.telemetry.counter("fleet.load_shed",
                                   reason="all_candidates_shed")
            raise FleetShedError(
                "every routing candidate shed the request",
                retry_after_s=(last_shed.retry_after_s
                               if last_shed is not None
                               and last_shed.retry_after_s
                               else self.policy.retry_after_s(
                                   self.cfg.retry_after_default_s)))
        except BaseException:
            self._release()
            raise

    # --- ingest fan-out -------------------------------------------------
    def ingest(self, bars, present, trace_id: Optional[str] = None,
               timeout: Optional[float] = 60.0) -> dict:
        """Broadcast one minute-bar micro-batch to every live stream
        replica. Per-replica failure isolation: each leg's error stays
        its own — the call only raises (:class:`FleetShedError`) when
        NO leg applied. Returns ``{"minute", "bars", "replicas":
        {label: leg}, "failed": [...], "trace_id"}`` where a skipped
        (demoted) replica's leg says so — the pod health view's
        evidence."""
        tid = canonical_trace_id(trace_id)
        # ONE normalization before the fan-out — the module's declared
        # boundary sync; every replica then ingests the same buffers
        bars = np.asarray(bars, np.float32)
        present = np.asarray(present, bool)
        stream_replicas = [r for r in self.replicas if r.stream]
        if not stream_replicas:
            raise ValueError("ingest needs at least one stream-enabled "
                             "replica (fleet built with stream=True)")
        live = {r.label for r in
                self.policy.candidates(stream_only=True)}
        legs: Dict[str, dict] = {}
        futures = {}
        for r in stream_replicas:
            if r.label not in live:
                legs[r.label] = {"ok": False, "skipped": True,
                                 "state": self.policy.state(r.label)}
                self.telemetry.counter("fleet.ingest_legs",
                                       outcome="skipped")
                continue
            try:
                futures[r.label] = r.server.ingest(bars, present,
                                                   trace_id=tid)
            except (LoadShedError, ValueError, RuntimeError) as e:
                legs[r.label] = {"ok": False,
                                 "error": f"{type(e).__name__}: {e}"}
                self.telemetry.counter("fleet.ingest_legs",
                                       outcome="shed")
                self.policy.note_result(r.label, ok=False)
        for label, fut in futures.items():
            try:
                res = fut.result(timeout)
                legs[label] = {"ok": True, "minute": res["minute"]}
                self.telemetry.counter("fleet.ingest_legs",
                                       outcome="ok")
                self.policy.note_result(label, ok=True)
            except Exception as e:  # noqa: BLE001 — isolate the leg
                legs[label] = {"ok": False,
                               "error": f"{type(e).__name__}: {e}"}
                self.telemetry.counter("fleet.ingest_legs",
                                       outcome="failed")
                self.policy.note_result(label, ok=False)
        ok_minutes = [leg["minute"] for leg in legs.values()
                      if leg.get("ok")]
        failed = sorted(l_ for l_, leg in legs.items()
                        if not leg.get("ok"))
        self.telemetry.counter("fleet.ingest_fanout")
        self.telemetry.request({
            "trace_id": tid, "op": "ingest_fanout",
            "status": "ok" if ok_minutes else "error",
            "data": {"legs": len(legs), "failed": failed}})
        if not ok_minutes:
            self.telemetry.counter("fleet.load_shed",
                                   reason="ingest_all_legs")
            raise FleetShedError(
                f"ingest fan-out failed on every stream replica "
                f"({failed})",
                retry_after_s=self.policy.retry_after_s(
                    self.cfg.retry_after_default_s))
        return {"trace_id": tid, "minute": max(ok_minutes),
                "bars": int(present.sum()), "replicas": legs,
                "failed": failed}


class FactorFleet:
    """N FactorServer replicas over disjoint submeshes as ONE pod:
    replicas + shed policy + router composed, with the pod health and
    metrics views the front door (:mod:`.http`) serves.

    The pod control plane (router/policy counters, pod request records)
    lives on ``telemetry`` — its own stream, folded together with the
    per-replica registries by :func:`.http.pod_registry`.
    """

    def __init__(self, source, n_replicas: int,
                 names: Optional[Sequence[str]] = None,
                 serve_cfg=None, fleet_cfg: Optional[FleetConfig] = None,
                 replicate_quirks: bool = True,
                 rolling_impl: Optional[str] = None,
                 stream: bool = False,
                 stream_batches: Sequence[int] = (1,),
                 start: bool = True, telemetry=None,
                 devices: Optional[Sequence] = None):
        from ..telemetry import Telemetry
        self.source = source
        self.telemetry = (telemetry if telemetry is not None
                          else Telemetry())
        self.cfg = fleet_cfg or FleetConfig()
        self.replicas = build_replicas(
            source, n_replicas, devices=devices, names=names,
            serve_cfg=serve_cfg, replicate_quirks=replicate_quirks,
            rolling_impl=rolling_impl, stream=stream,
            stream_batches=stream_batches, start=start)
        self.policy = ShedPolicy(
            self.replicas, telemetry=self.telemetry,
            cooldown_s=self.cfg.demote_cooldown_s,
            hbm_headroom_frac=self.cfg.hbm_headroom_frac)
        self.router = FleetRouter(self.replicas, self.policy,
                                  telemetry=self.telemetry,
                                  cfg=self.cfg)
        self.telemetry.gauge("fleet.replicas", len(self.replicas))
        self._t_start = time.monotonic()
        #: pod SLO plane (ISSUE 16): the fleet owns its OWN flight
        #: recorder (pod-level ``slo_burn`` captures carry the
        #: router's route/ingest_fanout request records) and a
        #: sampler over the control-plane registry + derived
        #: per-replica liveness/freshness signals. Replica-level
        #: timelines run inside each FactorServer and are folded
        #: offline by ``telemetry.aggregate``.
        from ..telemetry.opsplane import FlightRecorder
        from ..telemetry.slo import fleet_objectives
        self.flight = FlightRecorder(telemetry=self.telemetry,
                                     dump_dir=self.cfg.flight_dir)
        self.timeline = self.telemetry.timeline
        self.sloplane = self.telemetry.sloplane
        self.timeline.add_source(self._pod_signals)
        has_stream = any(r.stream for r in self.replicas)
        self.sloplane.configure(
            fleet_objectives(staleness_s=self.cfg.slo_staleness_s,
                             streaming=has_stream),
            flight=self.flight, timeline=self.timeline,
            time_scale=self.cfg.slo_time_scale)
        if self.cfg.timeline_sample_period_s > 0:
            self.timeline.start(self.cfg.timeline_sample_period_s)

    def _pod_signals(self) -> dict:
        """Derived pod signals for the timeline sampler: live-replica
        count, per-replica liveness, and the worst live carry's
        ingest staleness — host-side policy/engine mirrors only."""
        states = self.policy.snapshot()["states"]
        out = {"fleet.live_replicas":
               float(sum(1 for s in states.values()
                         if s != "demoted"))}
        for label, state in states.items():
            out[f"fleet.replica_up{{replica={label}}}"] = (
                0.0 if state == "demoted" else 1.0)
        staleness = []
        for r in self.replicas:
            eng = getattr(r.server, "stream_engine", None)
            if eng is None:
                continue
            s = eng.staleness_s()
            if s is not None:
                staleness.append(s)
        if staleness:
            out["fleet.stream_staleness_s"] = round(max(staleness), 6)
        return out

    # --- request surface (the router's, re-exported) --------------------
    def submit(self, q: Query, trace_id: Optional[str] = None):
        return self.router.submit(q, trace_id=trace_id)

    def ingest(self, bars, present, trace_id: Optional[str] = None,
               timeout: Optional[float] = 60.0) -> dict:
        return self.router.ingest(bars, present, trace_id=trace_id,
                                  timeout=timeout)

    # --- pod views ------------------------------------------------------
    def health(self) -> dict:
        """Per-replica ``healthz`` payloads (the ISSUE 11 shared shape)
        + the pod rollup: live/demoted counts, policy states, stream
        cursor skew across the live carries."""
        pod_state = self.policy.snapshot()
        reps = {r.label: r.health() for r in self.replicas}
        live = [l_ for l_, s in pod_state["states"].items()
                if s != "demoted"]
        payload = {
            "ok": bool(live),
            "replicas": reps,
            "pod": {
                "replicas": len(self.replicas),
                "live": len(live),
                "demoted": pod_state["demoted"],
                "states": pod_state["states"],
                "reasons": pod_state["reasons"],
                "inflight": self.router.inflight(),
                "uptime_s": round(time.monotonic() - self._t_start, 3),
            },
        }
        minutes = [h["stream_minute"] for h in reps.values()
                   if "stream_minute" in h]
        if minutes:
            payload["pod"]["stream_minute"] = max(minutes)
            payload["pod"]["stream_minute_skew"] = (max(minutes)
                                                    - min(minutes))
        # ISSUE 16 satellite: the pod's freshness is its WORST
        # replica's wall-clock ingest staleness (read verbatim from
        # the shared healthz key; replicas that never ingested
        # report None and don't count)
        staleness = [h["stream_staleness_s"] for h in reps.values()
                     if h.get("stream_staleness_s") is not None]
        if staleness:
            payload["pod"]["stream_staleness_s"] = max(staleness)
        # pod factor-health rollup (ISSUE 12): the worst-coverage
        # factor PER REPLICA (read verbatim from the shared healthz
        # shape — nothing translated) with the stream cursor skew
        # beside it: a replica whose data quality collapsed and a
        # replica whose carry fell behind are the same triage page
        fh = {}
        for label, h in reps.items():
            block = h.get("factor_health") or {}
            fh[label] = {
                "available": bool(block.get("available")),
                "worst_coverage": block.get("worst_coverage"),
                "widen_rate": block.get("widen_rate"),
                "drift_bursts": (block.get("drift") or {}).get("bursts"),
            }
        payload["pod"]["factor_health"] = {
            "replicas": fh,
            "stream_minute_skew": payload["pod"].get(
                "stream_minute_skew"),
        }
        return payload

    def pod_registry(self):
        """The pod metrics view: the control plane + every replica
        registry through ``telemetry.aggregate``'s registry-merge fold
        (counters exact — the PR 9 contract; see :func:`.http
        .pod_registry`)."""
        from .http import pod_registry
        return pod_registry(self)

    # --- lifecycle ------------------------------------------------------
    def start(self) -> "FactorFleet":
        for r in self.replicas:
            r.start()
        return self

    def close(self, timeout: float = 10.0) -> None:
        if self.cfg.timeline_sample_period_s > 0:
            self.timeline.stop()
        for r in self.replicas:
            r.close(timeout=timeout)

    def __enter__(self) -> "FactorFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
