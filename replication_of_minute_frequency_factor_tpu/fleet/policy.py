"""Shed/degrade policy: which replicas are routing candidates NOW.

Driven by the EXISTING signals only (ISSUE 11) — nothing here invents a
health model:

* **breaker state** — :meth:`..serve.service.FactorServer.breaker_state`
  (``open`` demotes; the replica's own half-open probe logic stays the
  per-replica arbiter);
* **HBM headroom** — the replica telemetry's ``device.hbm_bytes_in_use``
  watermarks (:meth:`..fleet.replica.Replica.hbm_bytes`) against the
  exposure-cache byte budget scaled by ``hbm_headroom_frac``: a replica
  whose device bytes blow past what its cache budget explains is
  demoted before it OOMs mid-request. Only MEASURED watermarks demote
  (``available`` true) — a live-arrays estimate never drains a replica
  (the same availability contract as the regress HBM series).

The ladder per replica: ``candidate`` → (breaker open / HBM over) →
``demoted`` (drained: no routing, ingest fan-out skips it, the flight
recorder dumps naming it) → cooldown lapse → ``probing`` (re-admitted
to candidacy; the replica's own breaker arbitrates the half-open probe)
→ first completed request restores (``candidate``) or re-demotes.

Pod-level shed: :meth:`ShedPolicy.candidates` empty means EVERY replica
is out — the router raises a pod shed (503 + ``Retry-After`` derived
from the shortest remaining demotion cooldown).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

CANDIDATE = "candidate"
DEMOTED = "demoted"
PROBING = "probing"

#: graftlint Tier C concurrency contract (analysis/concurrency_tier.py;
#: runtime twin telemetry/lockcheck.py): the candidacy ladder is read
#: by every routed request and flipped by refresh/note_result from
#: whichever thread routes. ``_demote`` is the documented
#: caller-holds-lock helper — refresh() takes the lock for the state
#: flip and runs the dump outside it — so it is declared ``locked``:
#: exempt from the lexical GL-C1 check, still asserted at runtime.
GLC_CONTRACT = {
    "ShedPolicy": {
        "lock": "_lock",
        "guards": ("_state", "_until", "_reason"),
        "init": (),
        "locked": ("_demote",),
    },
}


class ShedPolicy:
    """Per-replica routing-candidacy state machine over the breaker +
    HBM signals. All transitions are counter/event-instrumented under
    ``fleet.*`` and a demotion force-dumps the replica's flight
    recorder with the replica named in the trigger extra."""

    def __init__(self, replicas, telemetry=None,
                 cooldown_s: float = 1.0,
                 hbm_headroom_frac: float = 1.5):
        from ..telemetry import get_telemetry
        self.replicas = list(replicas)
        self.telemetry = (telemetry if telemetry is not None
                          else get_telemetry())
        self.cooldown_s = float(cooldown_s)
        self.hbm_headroom_frac = float(hbm_headroom_frac)
        self._lock = threading.Lock()
        self._state: Dict[str, str] = {r.label: CANDIDATE
                                       for r in self.replicas}
        self._until: Dict[str, float] = {}
        self._reason: Dict[str, str] = {}
        from ..telemetry.lockcheck import maybe_install
        maybe_install(self)

    # --- signal reads ---------------------------------------------------
    def _hbm_over(self, replica) -> bool:
        in_use, available = replica.hbm_bytes()
        if not available:
            return False  # estimates never demote (ISSUE 8 contract)
        budget = (replica.server.scfg.cache_bytes
                  * self.hbm_headroom_frac)
        return budget > 0 and in_use > budget

    # --- transitions ----------------------------------------------------
    def _factor_health_audit(self, replica) -> dict:
        """The replica's factor-health snapshot at demote time (ISSUE
        12): MEASURED data quality joins the demote-signal audit trail
        — the event and the flight dump record what the factors looked
        like when the machine-level signal fired — but it is NOT a
        demote signal itself: only the breaker and measured HBM
        demote. Never raises (an audit read must not block a state
        flip)."""
        try:
            block = replica.telemetry.factorplane.summary()
            return {"available": bool(block.get("available")),
                    "worst_coverage": block.get("worst_coverage"),
                    "widen_rate": block.get("widen_rate"),
                    "drift_bursts": (block.get("drift")
                                     or {}).get("bursts")}
        except Exception:  # noqa: BLE001 — audit only
            return {"available": False}

    def _demote(self, replica, reason: str) -> None:
        """candidate/probing -> demoted (caller holds the lock for the
        state flip; the dump runs outside it)."""
        self._state[replica.label] = DEMOTED
        self._until[replica.label] = time.monotonic() + self.cooldown_s
        self._reason[replica.label] = reason
        self.telemetry.counter("fleet.demotions",
                               replica=replica.label, reason=reason)
        self.telemetry.event("fleet.demote", replica=replica.label,
                             reason=reason,
                             factor_health=self._factor_health_audit(
                                 replica))

    def refresh(self) -> None:
        """One pass over the signals: demote tripped/over-budget
        candidates, move cooled-down demoted replicas to probing."""
        dumps = []
        with self._lock:
            now = time.monotonic()
            for r in self.replicas:
                state = self._state[r.label]
                breaker = r.server.breaker_state()
                if state == CANDIDATE:
                    if breaker == "open":
                        self._demote(r, "breaker")
                        dumps.append((r, "breaker"))
                    elif self._hbm_over(r):
                        self._demote(r, "hbm")
                        dumps.append((r, "hbm"))
                elif state == DEMOTED:
                    if (now >= self._until.get(r.label, 0.0)
                            and breaker != "open"
                            and not self._hbm_over(r)):
                        self._state[r.label] = PROBING
                        self.telemetry.counter("fleet.probes",
                                               replica=r.label)
            self._note_gauges()
        for r, reason in dumps:
            # the anomaly evidence (ISSUE 11 acceptance): the demoted
            # replica's own flight recorder dumps its recent requests
            # with the demotion naming it — forced, outside the lock.
            # The factor-health snapshot rides as audit context (ISSUE
            # 12) — measured data quality at demote time, never a
            # demote signal
            r.server.flight.dump(
                "fleet_demote", force=True,
                extra={"replica": r.label, "reason": reason,
                       "factor_health": self._factor_health_audit(r)})

    def note_result(self, label: str, ok: bool) -> None:
        """A routed request's outcome: a probing replica is restored on
        success, re-demoted (fresh cooldown) on failure. Candidate
        failures are left to the replica's own breaker — the next
        refresh reads it."""
        with self._lock:
            if self._state.get(label) != PROBING:
                return
            if ok:
                self._state[label] = CANDIDATE
                self._until.pop(label, None)
                self._reason.pop(label, None)
                self.telemetry.counter("fleet.restores", replica=label)
                self.telemetry.event("fleet.restore", replica=label)
            else:
                self._state[label] = DEMOTED
                self._until[label] = time.monotonic() + self.cooldown_s
                self.telemetry.counter("fleet.demotions",
                                       replica=label,
                                       reason="probe_failed")
            self._note_gauges()

    def _note_gauges(self) -> None:
        live = sum(1 for s in self._state.values() if s != DEMOTED)
        self.telemetry.gauge("fleet.replicas_live", live)
        self.telemetry.gauge("fleet.replicas_demoted",
                             len(self._state) - live)

    # --- reads ----------------------------------------------------------
    def state(self, label: str) -> str:
        with self._lock:
            return self._state.get(label, DEMOTED)

    def candidates(self, stream_only: bool = False) -> List:
        """Routing-eligible replicas (candidate + probing) after a
        signal refresh; ``stream_only`` restricts to stream-enabled
        ones (the ingest fan-out's view). Empty means pod shed."""
        self.refresh()
        with self._lock:
            out = [r for r in self.replicas
                   if self._state[r.label] != DEMOTED
                   and (not stream_only or r.stream)]
        return out

    def retry_after_s(self, default: float = 1.0) -> float:
        """The pod shed's backoff hint: the SHORTEST remaining demotion
        cooldown (the soonest a probe could readmit a replica), else
        ``default``."""
        with self._lock:
            now = time.monotonic()
            remaining = [u - now for l_, u in self._until.items()
                         if self._state.get(l_) == DEMOTED]
        live = [r for r in remaining if r > 0]
        return min(live) if live else default

    def snapshot(self) -> dict:
        """The health rollup's view: per-replica state + demotion
        reasons."""
        with self._lock:
            return {
                "states": dict(self._state),
                "demoted": sorted(l_ for l_, s in self._state.items()
                                  if s == DEMOTED),
                "reasons": dict(self._reason),
            }
