"""The pod front door: one HTTP surface multiplexing N replicas.

Same stdlib-only shape as :mod:`..serve.http` (one thread per
connection feeding the replicas' micro-batch windows), same endpoints —
a client cannot tell a pod from a single server except by reading the
payloads:

* ``POST /v1/query`` — routed by the coalescing-affinity key
  (:meth:`..fleet.router.FleetRouter.submit`); 503 + ``Retry-After``
  when the POD sheds (every candidate out) exactly like a single
  server's breaker shed.
* ``POST /v1/ingest`` — the fan-out: 200 with the per-replica leg map
  as long as ANY leg applied (failure isolation is the point — the
  response SAYS which legs failed/skipped), 503 only when none did.
* ``GET /healthz`` — per-replica payloads (the shared ISSUE 11 shape)
  + the pod rollup (live/demoted, policy states, stream cursor skew,
  and the ISSUE 12 ``factor_health`` block: each replica's
  worst-coverage factor / widen rate / drift bursts read verbatim
  from its own healthz payload, with the stream cursor skew beside
  them).
* ``GET /v1/metrics`` — the POD registry: the control plane + every
  replica registry folded through ``telemetry.aggregate``'s
  registry-merge (:func:`pod_registry` — counters exact, the PR 9
  contract; never an ad-hoc merger). JSON by default, Prometheus text
  on content negotiation, same as the single server.
* ``GET /v1/slo`` — the POD SLO plane (ISSUE 16): the fleet's
  burn-rate objectives (availability over routed vs pod sheds, pod
  ingest freshness) as JSON, or the ``slo_*``-only Prometheus view of
  the CONTROL-PLANE registry under the same content negotiation.
* ``GET /v1/timeline?name=&since=`` — the pod timeline (ISSUE 16):
  control-plane rates + derived per-replica liveness/freshness
  series, same query surface as the single server.
* ``POST /v1/debug/dump`` — fans the on-demand flight capture out to
  every replica; returns ``{label: path}``.

Trace IDs: ``X-Trace-Id`` in/out as in :mod:`..serve.http`; the pod
assigns one ID at admission and the same ID crosses the router→replica
hop, so the two telemetry streams join on it.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from ..serve.http import (MAX_BODY_BYTES, MAX_INGEST_BODY_BYTES,
                          query_from_doc, render_answer,
                          retry_after_seconds, wants_prometheus)
from ..serve.service import LoadShedError, Query
from ..telemetry.opsplane import canonical_trace_id, to_prometheus
from .router import FactorFleet


def pod_registry(fleet: FactorFleet):
    """The pod metrics registry: the fleet control plane + every
    replica registry through :func:`..telemetry.aggregate
    .merge_registries` — the SAME fold the multihost bundle aggregator
    runs, so pod counter totals equal the per-replica sums by
    construction (re-verified, not assumed, in ``bench.fleet_smoke``
    and tests/test_fleet.py)."""
    from ..telemetry.aggregate import merge_registries
    return merge_registries(
        [fleet.telemetry.registry]
        + [r.telemetry.registry for r in fleet.replicas])


def fleet_get_payload(fleet: FactorFleet, path: str, query: dict,
                      accept: str = ""
                      ) -> Optional[Tuple[int, str, bytes]]:
    """The pod GET surface -> ``(status, content_type, body)`` or None
    for an unknown route — ONE implementation for the legacy binding
    and the evented edge (ISSUE 20), the fleet twin of
    :func:`..serve.http.get_payload`."""
    if path == "/healthz":
        return 200, "application/json", \
            json.dumps(fleet.health()).encode()
    if path == "/v1/metrics":
        reg = pod_registry(fleet)
        if wants_prometheus(accept, query):
            return 200, "text/plain; version=0.0.4; charset=utf-8", \
                to_prometheus(reg).encode()
        return 200, "application/json", \
            json.dumps(reg.snapshot()).encode()
    if path == "/v1/slo":
        if wants_prometheus(accept, query):
            from ..telemetry.slo import slo_prometheus
            return 200, "text/plain; version=0.0.4; charset=utf-8", \
                slo_prometheus(fleet.telemetry.registry).encode()
        return 200, "application/json", json.dumps({
            "slo": fleet.sloplane.summary(),
            "evaluation": fleet.sloplane.evaluate(),
        }).encode()
    if path == "/v1/timeline":
        try:
            name = query.get("name", [None])[0]
            since_raw = query.get("since", [None])[0]
            since = (float(since_raw) if since_raw is not None
                     else None)
            limit_raw = query.get("limit", [None])[0]
            limit = (int(limit_raw) if limit_raw is not None
                     else None)
        except (TypeError, ValueError) as e:
            return 400, "application/json", json.dumps(
                {"error": f"malformed timeline query: {e}"}).encode()
        frames = fleet.timeline.query(name=name, since=since,
                                      limit=limit)
        return 200, "application/json", json.dumps(
            {"frames": frames, "count": len(frames)}).encode()
    return None


def _dump_doc(fleet: FactorFleet) -> Tuple[int, dict]:
    """The fan-out flight capture shared by both front doors."""
    paths = {}
    for r in fleet.replicas:
        try:
            paths[r.label] = r.server.debug_dump()
        except Exception as e:  # noqa: BLE001 — best-effort
            paths[r.label] = f"error: {type(e).__name__}: {e}"
    if all(p is None for p in paths.values()):
        return 409, {"error": "no flight dump directory configured "
                              "on any replica "
                              "(ServeConfig.flight_dir)"}
    return 200, {"paths": paths}


def _make_handler(fleet: FactorFleet, timeout: Optional[float]):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _reply(self, code: int, payload: dict,
                   trace_id: Optional[str] = None,
                   retry_after_s: Optional[float] = None) -> None:
            self._reply_bytes(code, json.dumps(payload).encode(),
                              "application/json", trace_id,
                              retry_after_s=retry_after_s)

        def _reply_bytes(self, code: int, body: bytes,
                         content_type: str,
                         trace_id: Optional[str] = None,
                         retry_after_s: Optional[float] = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            if trace_id:
                self.send_header("X-Trace-Id", trace_id)
            if retry_after_s is not None:
                self.send_header("Retry-After",
                                 str(retry_after_seconds(retry_after_s)))
            self.end_headers()
            self.wfile.write(body)

        def _trace_id(self) -> str:
            return canonical_trace_id(self.headers.get("X-Trace-Id"))

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            # ISSUE 20: the whole GET surface is the shared
            # fleet_get_payload builder — the edge serves the same
            # bytes by construction
            parsed = urllib.parse.urlparse(self.path)
            res = fleet_get_payload(fleet, parsed.path,
                                    urllib.parse.parse_qs(parsed.query),
                                    self.headers.get("Accept", ""))
            if res is None:
                self._reply(404, {"error": f"no route {self.path}"})
                return
            status, ctype, body = res
            self._reply_bytes(status, body, ctype)

        def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
            if self.path == "/v1/ingest":
                self._post_ingest()
                return
            if self.path == "/v1/debug/dump":
                self._post_dump()
                return
            if self.path != "/v1/query":
                self._reply(404, {"error": f"no route {self.path}"})
                return
            tid = self._trace_id()
            try:
                length = int(self.headers.get("Content-Length", "0"))
                if length > MAX_BODY_BYTES:
                    self._reply(413, {"error": "body too large"}, tid)
                    return
                doc = json.loads(self.rfile.read(length) or b"{}")
                # ISSUE 20: the ONE parser both serve front doors use
                # (wire encoding negotiated from Accept / the body)
                q = query_from_doc(doc, self.headers.get("Accept", ""))
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {"error": f"malformed request: {e}"},
                            tid)
                return
            try:
                fut = fleet.submit(q, trace_id=tid)
            except LoadShedError as e:
                self._reply(503, {"error": str(e), "shed": True}, tid,
                            retry_after_s=e.retry_after_s)
                return
            except ValueError as e:
                self._reply(400, {"error": str(e)}, tid)
                return
            try:
                ctype, body = render_answer(fut.result(timeout), q)
                self._reply_bytes(200, body, ctype, tid)
            except Exception as e:  # noqa: BLE001 — dispatch failure
                self._reply(500, {"error": f"{type(e).__name__}: {e}"},
                            tid)

        def _post_ingest(self):
            tid = self._trace_id()
            try:
                length = int(self.headers.get("Content-Length", "0"))
                if length > MAX_INGEST_BODY_BYTES:
                    self._reply(413, {"error": "body too large"}, tid)
                    return
                doc = json.loads(self.rfile.read(length) or b"{}")
                bars, present = doc["bars"], doc["present"]
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {"error": f"malformed ingest: {e}"},
                            tid)
                return
            try:
                res = fleet.ingest(bars, present, trace_id=tid,
                                   timeout=timeout)
            except LoadShedError as e:
                self._reply(503, {"error": str(e), "shed": True}, tid,
                            retry_after_s=e.retry_after_s)
                return
            except ValueError as e:
                self._reply(400, {"error": str(e)}, tid)
                return
            self._reply(200, res, tid)

        def _post_dump(self):
            status, doc = _dump_doc(fleet)
            self._reply(status, doc)

    return Handler


def serve_fleet_http(fleet: FactorFleet, host: str = "127.0.0.1",
                     port: int = 0, timeout: Optional[float] = 60.0,
                     ) -> Tuple[ThreadingHTTPServer, threading.Thread]:
    """Bind the pod on ``host:port`` (0 = ephemeral) and serve from a
    daemon thread — the fleet twin of :func:`..serve.http.serve_http`;
    stop with ``httpd.shutdown()``."""
    httpd = ThreadingHTTPServer((host, port),
                                _make_handler(fleet, timeout))
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever, daemon=True,
                              name="factor-fleet-http")
    thread.start()
    return httpd, thread


class FleetEdgeBackend:
    """Adapts one :class:`FactorFleet` to the evented edge's backend
    protocol (ISSUE 20; see ``..serve.edge``). The pod's ingest
    fan-out is SYNCHRONOUS by contract (it waits every leg's future to
    build the per-leg map), so it runs as an aux-thread call — the
    loop thread never blocks on a replica."""

    label = "fleet"

    def __init__(self, fleet: FactorFleet,
                 timeout: Optional[float] = 60.0):
        self.fleet = fleet
        self.timeout = timeout

    @property
    def telemetry(self):
        return self.fleet.telemetry

    def get(self, path: str, query: dict, accept: str
            ) -> Optional[Tuple[int, str, bytes]]:
        return fleet_get_payload(self.fleet, path, query, accept)

    def submit_query(self, q: Query, tid):
        return self.fleet.submit(q, trace_id=tid)

    def post(self, path: str, doc: dict, tid):
        if path == "/v1/ingest":
            bars, present = doc["bars"], doc["present"]
            fleet, timeout = self.fleet, self.timeout

            def ingest():
                return 200, fleet.ingest(bars, present, trace_id=tid,
                                         timeout=timeout)

            return "call", ingest
        if path == "/v1/debug/dump":
            fleet = self.fleet

            def dump():
                return _dump_doc(fleet)

            return "call", dump
        return None

    def max_body(self, path: str) -> int:
        return (MAX_INGEST_BODY_BYTES if path == "/v1/ingest"
                else MAX_BODY_BYTES)


def serve_fleet_edge(fleet: FactorFleet, host: str = "127.0.0.1",
                     port: int = 0,
                     timeout: Optional[float] = 60.0):
    """Bind the evented front door over one pod — the fleet twin of
    :func:`..serve.edge.serve_edge`; quota/idle knobs come from
    ``FleetConfig``. Returns the running ``EdgeServer``."""
    from ..serve.edge import EdgeServer
    cfg = fleet.cfg
    backend = FleetEdgeBackend(fleet, timeout)
    return EdgeServer(backend, host=host, port=port,
                      quota_rps=cfg.tenant_quota_rps,
                      quota_burst=cfg.tenant_quota_burst,
                      idle_timeout_s=cfg.edge_idle_timeout_s)


def serve_fleet_frontdoor(fleet: FactorFleet, host: str = "127.0.0.1",
                          port: int = 0,
                          timeout: Optional[float] = 60.0,
                          transport: Optional[str] = None):
    """Bind the CONFIGURED pod front door (``FleetConfig.edge``; the
    fleet twin of :func:`..serve.http.serve_frontdoor`). Returns an
    object with ``.server_address`` and ``.shutdown()`` either way."""
    transport = transport or fleet.cfg.edge
    if transport == "legacy":
        httpd, _thread = serve_fleet_http(fleet, host=host, port=port,
                                          timeout=timeout)
        return httpd
    if transport != "edge":
        raise ValueError(f"unknown front-door transport {transport!r} "
                         "(edge or legacy)")
    return serve_fleet_edge(fleet, host=host, port=port,
                            timeout=timeout)
