"""The pod front door: one HTTP surface multiplexing N replicas.

Same stdlib-only shape as :mod:`..serve.http` (one thread per
connection feeding the replicas' micro-batch windows), same endpoints —
a client cannot tell a pod from a single server except by reading the
payloads:

* ``POST /v1/query`` — routed by the coalescing-affinity key
  (:meth:`..fleet.router.FleetRouter.submit`); 503 + ``Retry-After``
  when the POD sheds (every candidate out) exactly like a single
  server's breaker shed.
* ``POST /v1/ingest`` — the fan-out: 200 with the per-replica leg map
  as long as ANY leg applied (failure isolation is the point — the
  response SAYS which legs failed/skipped), 503 only when none did.
* ``GET /healthz`` — per-replica payloads (the shared ISSUE 11 shape)
  + the pod rollup (live/demoted, policy states, stream cursor skew,
  and the ISSUE 12 ``factor_health`` block: each replica's
  worst-coverage factor / widen rate / drift bursts read verbatim
  from its own healthz payload, with the stream cursor skew beside
  them).
* ``GET /v1/metrics`` — the POD registry: the control plane + every
  replica registry folded through ``telemetry.aggregate``'s
  registry-merge (:func:`pod_registry` — counters exact, the PR 9
  contract; never an ad-hoc merger). JSON by default, Prometheus text
  on content negotiation, same as the single server.
* ``GET /v1/slo`` — the POD SLO plane (ISSUE 16): the fleet's
  burn-rate objectives (availability over routed vs pod sheds, pod
  ingest freshness) as JSON, or the ``slo_*``-only Prometheus view of
  the CONTROL-PLANE registry under the same content negotiation.
* ``GET /v1/timeline?name=&since=`` — the pod timeline (ISSUE 16):
  control-plane rates + derived per-replica liveness/freshness
  series, same query surface as the single server.
* ``POST /v1/debug/dump`` — fans the on-demand flight capture out to
  every replica; returns ``{label: path}``.

Trace IDs: ``X-Trace-Id`` in/out as in :mod:`..serve.http`; the pod
assigns one ID at admission and the same ID crosses the router→replica
hop, so the two telemetry streams join on it.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from ..serve.http import (MAX_BODY_BYTES, MAX_INGEST_BODY_BYTES,
                          retry_after_seconds)
from ..serve.service import LoadShedError, Query
from ..telemetry.opsplane import canonical_trace_id, to_prometheus
from .router import FactorFleet


def pod_registry(fleet: FactorFleet):
    """The pod metrics registry: the fleet control plane + every
    replica registry through :func:`..telemetry.aggregate
    .merge_registries` — the SAME fold the multihost bundle aggregator
    runs, so pod counter totals equal the per-replica sums by
    construction (re-verified, not assumed, in ``bench.fleet_smoke``
    and tests/test_fleet.py)."""
    from ..telemetry.aggregate import merge_registries
    return merge_registries(
        [fleet.telemetry.registry]
        + [r.telemetry.registry for r in fleet.replicas])


def _make_handler(fleet: FactorFleet, timeout: Optional[float]):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _reply(self, code: int, payload: dict,
                   trace_id: Optional[str] = None,
                   retry_after_s: Optional[float] = None) -> None:
            self._reply_bytes(code, json.dumps(payload).encode(),
                              "application/json", trace_id,
                              retry_after_s=retry_after_s)

        def _reply_bytes(self, code: int, body: bytes,
                         content_type: str,
                         trace_id: Optional[str] = None,
                         retry_after_s: Optional[float] = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            if trace_id:
                self.send_header("X-Trace-Id", trace_id)
            if retry_after_s is not None:
                self.send_header("Retry-After",
                                 str(retry_after_seconds(retry_after_s)))
            self.end_headers()
            self.wfile.write(body)

        def _trace_id(self) -> str:
            return canonical_trace_id(self.headers.get("X-Trace-Id"))

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            parsed = urllib.parse.urlparse(self.path)
            if parsed.path == "/healthz":
                self._reply(200, fleet.health())
                return
            if parsed.path == "/v1/metrics":
                accept = self.headers.get("Accept", "")
                query = urllib.parse.parse_qs(parsed.query)
                want_text = ("text/plain" in accept
                             or "openmetrics" in accept
                             or query.get("format", [""])[0]
                             == "prometheus")
                reg = pod_registry(fleet)
                if want_text:
                    self._reply_bytes(
                        200, to_prometheus(reg).encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
                else:
                    self._reply(200, reg.snapshot())
                return
            if parsed.path == "/v1/slo":
                accept = self.headers.get("Accept", "")
                query = urllib.parse.parse_qs(parsed.query)
                want_text = ("text/plain" in accept
                             or "openmetrics" in accept
                             or query.get("format", [""])[0]
                             == "prometheus")
                if want_text:
                    from ..telemetry.slo import slo_prometheus
                    body = slo_prometheus(
                        fleet.telemetry.registry).encode()
                    self._reply_bytes(
                        200, body,
                        "text/plain; version=0.0.4; charset=utf-8")
                else:
                    self._reply(200, {
                        "slo": fleet.sloplane.summary(),
                        "evaluation": fleet.sloplane.evaluate(),
                    })
                return
            if parsed.path == "/v1/timeline":
                query = urllib.parse.parse_qs(parsed.query)
                try:
                    name = query.get("name", [None])[0]
                    since_raw = query.get("since", [None])[0]
                    since = (float(since_raw)
                             if since_raw is not None else None)
                    limit_raw = query.get("limit", [None])[0]
                    limit = (int(limit_raw)
                             if limit_raw is not None else None)
                except (TypeError, ValueError) as e:
                    self._reply(400,
                                {"error": f"malformed timeline "
                                          f"query: {e}"})
                    return
                frames = fleet.timeline.query(name=name, since=since,
                                              limit=limit)
                self._reply(200, {"frames": frames,
                                  "count": len(frames)})
                return
            self._reply(404, {"error": f"no route {self.path}"})

        def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
            if self.path == "/v1/ingest":
                self._post_ingest()
                return
            if self.path == "/v1/debug/dump":
                self._post_dump()
                return
            if self.path != "/v1/query":
                self._reply(404, {"error": f"no route {self.path}"})
                return
            tid = self._trace_id()
            try:
                length = int(self.headers.get("Content-Length", "0"))
                if length > MAX_BODY_BYTES:
                    self._reply(413, {"error": "body too large"}, tid)
                    return
                doc = json.loads(self.rfile.read(length) or b"{}")
                q = Query(
                    kind=doc.get("kind", ""),
                    start=int(doc.get("start", 0)),
                    end=int(doc.get("end", 0)),
                    names=(tuple(doc["names"]) if doc.get("names")
                           else None),
                    factor=doc.get("factor"),
                    horizon=int(doc.get("horizon", 1)),
                    group_num=int(doc.get("group_num", 5)))
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._reply(400, {"error": f"malformed request: {e}"},
                            tid)
                return
            try:
                fut = fleet.submit(q, trace_id=tid)
            except LoadShedError as e:
                self._reply(503, {"error": str(e), "shed": True}, tid,
                            retry_after_s=e.retry_after_s)
                return
            except ValueError as e:
                self._reply(400, {"error": str(e)}, tid)
                return
            try:
                self._reply(200, fut.result(timeout), tid)
            except Exception as e:  # noqa: BLE001 — dispatch failure
                self._reply(500, {"error": f"{type(e).__name__}: {e}"},
                            tid)

        def _post_ingest(self):
            tid = self._trace_id()
            try:
                length = int(self.headers.get("Content-Length", "0"))
                if length > MAX_INGEST_BODY_BYTES:
                    self._reply(413, {"error": "body too large"}, tid)
                    return
                doc = json.loads(self.rfile.read(length) or b"{}")
                bars, present = doc["bars"], doc["present"]
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {"error": f"malformed ingest: {e}"},
                            tid)
                return
            try:
                res = fleet.ingest(bars, present, trace_id=tid,
                                   timeout=timeout)
            except LoadShedError as e:
                self._reply(503, {"error": str(e), "shed": True}, tid,
                            retry_after_s=e.retry_after_s)
                return
            except ValueError as e:
                self._reply(400, {"error": str(e)}, tid)
                return
            self._reply(200, res, tid)

        def _post_dump(self):
            paths = {}
            for r in fleet.replicas:
                try:
                    paths[r.label] = r.server.debug_dump()
                except Exception as e:  # noqa: BLE001 — best-effort
                    paths[r.label] = f"error: {type(e).__name__}: {e}"
            if all(p is None for p in paths.values()):
                self._reply(409, {"error": "no flight dump directory "
                                           "configured on any replica "
                                           "(ServeConfig.flight_dir)"})
                return
            self._reply(200, {"paths": paths})

    return Handler


def serve_fleet_http(fleet: FactorFleet, host: str = "127.0.0.1",
                     port: int = 0, timeout: Optional[float] = 60.0,
                     ) -> Tuple[ThreadingHTTPServer, threading.Thread]:
    """Bind the pod on ``host:port`` (0 = ephemeral) and serve from a
    daemon thread — the fleet twin of :func:`..serve.http.serve_http`;
    stop with ``httpd.shutdown()``."""
    httpd = ThreadingHTTPServer((host, port),
                                _make_handler(fleet, timeout))
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever, daemon=True,
                              name="factor-fleet-http")
    thread.start()
    return httpd, thread
