"""Replica lifecycle: N :class:`..serve.service.FactorServer` s over
disjoint device submeshes.

A *replica* is one resident FactorServer pinned to its own slice of
``jax.devices()`` (:func:`partition_devices` — disjoint by
construction, validated on the 8-virtual-CPU-device harness the sharded
tests run on) with its OWN :class:`..telemetry.Telemetry`. The replica
index/label ride the schema-v3 multihost identity stamps
(``process_index``/``host``, ISSUE 9) on every bundle the replica
writes, so ``telemetry.aggregate`` folds a fleet's bundles exactly like
a multihost pod's — the fleet IS a pod, in-process.

Health is the existing ``healthz`` surface: :meth:`Replica.health`
returns :meth:`..serve.service.FactorServer.health` verbatim (the
ISSUE 11 shape with the ``replica`` identity block), plus
:meth:`Replica.probe_device` — a device-liveness probe that blocks on a
tiny put to the replica's lead device.

graftlint note (docs/static-analysis.md): this module is a declared
GL-A3 *boundary module* of the ``fleet/`` layer — its one allowed host
sync is the ``.block_until_ready()`` of the liveness probe. Everything
else in the layer stays sync-free; the answer materialization stays
``serve/service.py``'s declared sync.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..serve.service import FactorServer, ServeConfig
from ..telemetry import Telemetry


def partition_devices(n_replicas: int, devices: Optional[Sequence] = None
                      ) -> List[tuple]:
    """``n_replicas`` DISJOINT contiguous device groups out of
    ``devices`` (default ``jax.devices()``): ``len(devices) //
    n_replicas`` devices each, remainder devices left unassigned (a
    9-device host at N=4 runs 4×2 and idles one — the partition is
    uniform so no replica is a structural straggler). Raises when the
    host has fewer devices than replicas: a fleet with shared devices
    would serialize on the hardware while reporting parallelism."""
    if n_replicas < 1:
        raise ValueError(f"n_replicas must be >= 1 (got {n_replicas})")
    if devices is None:
        import jax
        devices = jax.devices()
    devices = list(devices)
    if n_replicas > len(devices):
        raise ValueError(
            f"cannot partition {len(devices)} device(s) into "
            f"{n_replicas} disjoint replica submeshes")
    per = len(devices) // n_replicas
    return [tuple(devices[i * per:(i + 1) * per])
            for i in range(n_replicas)]


class Replica:
    """One fleet member: a FactorServer over its submesh, its own
    telemetry, and the identity the pod planes address it by."""

    def __init__(self, index: int, devices: Sequence, source,
                 names: Optional[Sequence[str]] = None,
                 serve_cfg: Optional[ServeConfig] = None,
                 replicate_quirks: bool = True,
                 rolling_impl: Optional[str] = None,
                 stream: bool = False,
                 stream_batches: Sequence[int] = (1,),
                 start: bool = True,
                 label: Optional[str] = None):
        self.index = int(index)
        self.label = label or f"r{self.index}"
        self.devices: Tuple = tuple(devices)
        if not self.devices:
            raise ValueError(f"replica {self.label} got an empty "
                             "device set")
        #: per-replica telemetry: counters/spans/requests of this
        #: replica only — the pod view is the registry-merge fold over
        #: these (fleet/http.py), never a shared mutable registry
        self.telemetry = Telemetry()
        self.stream = bool(stream)
        self.server = FactorServer(
            source, names=names, serve_cfg=serve_cfg,
            replicate_quirks=replicate_quirks,
            rolling_impl=rolling_impl, telemetry=self.telemetry,
            start=start, stream=stream, stream_batches=stream_batches,
            replica_label=self.label, devices=self.devices)

    # --- health ---------------------------------------------------------
    def health(self) -> dict:
        """The replica's ``healthz`` payload — exactly the standalone
        server's shape (ISSUE 11 satellite), so the pod rollup is a
        dict of these."""
        return self.server.health()

    def probe_device(self) -> bool:
        """Device liveness: put one scalar on the submesh lead and
        block until it lands. The ``.block_until_ready()`` is this
        module's one declared GL-A3 boundary sync — a wedged device
        surfaces here (False), not as a hung request inside the worker
        loop."""
        try:
            import jax
            jax.device_put(np.float32(1.0),
                           self.devices[0]).block_until_ready()
            return True
        except Exception:  # noqa: BLE001 — the probe's job is the bool
            self.telemetry.counter("fleet.device_probe_failures",
                                   replica=self.label)
            return False

    def hbm_bytes(self) -> Tuple[float, bool]:
        """``(bytes_in_use summed over this replica's devices,
        available)`` from the replica telemetry's last HBM watermark
        sample — the headroom signal the shed policy demotes on. Plain
        dict reads; never a device sync."""
        summary = self.telemetry.hbm.summary()
        keys = {f"{d.platform}:{d.id}" for d in self.devices}
        total = sum(v.get("bytes_in_use", 0)
                    for k, v in (summary.get("devices") or {}).items()
                    if k in keys)
        return float(total), bool(summary.get("available"))

    # --- bundles (the pod aggregation leg) ------------------------------
    def write_bundle(self, out_dir: str, cfg=None) -> dict:
        """Write this replica's telemetry bundle stamped with its
        identity (``process_index=index``, ``host=label`` — the
        schema-v3 stamps), so ``telemetry.aggregate`` folds fleet
        bundles exactly like multihost ones. Returns the artifact
        paths."""
        return self.telemetry.write(out_dir, cfg=cfg,
                                    process_index=self.index,
                                    host=self.label)

    # --- lifecycle ------------------------------------------------------
    def start(self) -> "Replica":
        self.server.start()
        return self

    def close(self, timeout: float = 10.0) -> None:
        self.server.close(timeout=timeout)

    def __repr__(self) -> str:  # debug/demo friendliness
        return (f"Replica({self.label}, devices="
                f"{[str(d) for d in self.devices]})")


def build_replicas(source, n_replicas: int,
                   devices: Optional[Sequence] = None,
                   **replica_kwargs) -> List[Replica]:
    """``n_replicas`` Replicas over :func:`partition_devices`' disjoint
    submeshes, indices/labels assigned in device order."""
    groups = partition_devices(n_replicas, devices)
    return [Replica(i, g, source, **replica_kwargs)
            for i, g in enumerate(groups)]
