"""Device mesh and sharding layout for the day-batch tensor.

Layout: ``bars [D, T, 240, 5]`` and ``mask [D, T, 240]`` shard over a 2-D
logical mesh ``(days, tickers)``. Factor kernels are pure per-(day, ticker)
maps, so both axes are data-parallel for L1; the per-date cross-sectional
stage (L3) keeps the days axis data-parallel and turns the tickers axis into
a collective axis (see collectives.py).

Replaces reference joblib fan-out (MinuteFrequentFactorCICC.py:85-94): one
process per day-file becomes one mesh coordinate per (day-shard,
ticker-shard), with ICI collectives instead of filesystem round-trips.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DAYS_AXIS = "days"
TICKERS_AXIS = "tickers"


def make_mesh(
    shape: Optional[Tuple[int, int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ``(days, tickers)`` mesh over the available devices.

    Default shape ``(1, n_devices)``: the ticker axis is the wide one
    (~5000 tickers vs. a handful of days per batch) and per-stock kernels
    need zero communication, so all ICI bandwidth is reserved for the small
    cross-sectional collectives.
    """
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    if shape is None:
        shape = (1, devices.size)
    if shape[0] * shape[1] != devices.size:
        raise ValueError(
            f"mesh shape {shape} does not match {devices.size} devices")
    return Mesh(devices.reshape(shape), (DAYS_AXIS, TICKERS_AXIS))


def resident_mesh(
    n_shards: Optional[int] = None,
    devices: Optional[Sequence] = None,
    shape: Optional[Tuple[int, int]] = None,
) -> Mesh:
    """The resident-scan callers' mesh: ``(1, n)`` tickers-only by
    default, or a full 2-D ``(d, t)`` via ``shape`` (ISSUE 13).

    The streaming pipeline's mesh guard rejects any days dimension
    (batch day counts vary there); the resident scan's batch list is
    fixed up front, so it may shard BOTH axes — the scan axis is
    batches, the wide data-parallel axes are each batch's days and
    tickers, and the per-shard bodies need zero collectives outside
    the ``doc_pdf*`` rank gather (tickers axis) and the cross-day
    carry handoff leg (days axis; ``collectives.
    xs_carry_handoff_local``). ``n_shards=None`` with no ``shape``
    uses every local device on the ``(1, n)`` layout.
    """
    if devices is None:
        devices = jax.devices()
    if shape is not None:
        d, t = int(shape[0]), int(shape[1])
        if d < 1 or t < 1 or d * t > len(devices):
            raise ValueError(
                f"resident mesh shape {shape} needs {d * t} devices; "
                f"{len(devices)} visible")
        return make_mesh((d, t), devices[:d * t])
    if n_shards is None:
        n_shards = len(devices)
    return make_mesh((1, n_shards), devices[:n_shards])


def packed_year_spec() -> P:
    """PartitionSpec for a stacked packed-buffer year ``[N, S, L]``
    (batches x shards x per-shard packed bytes): the shard axis maps
    onto the mesh tickers axis, batches and bytes stay whole. The
    host-side twin of :func:`..data.wire.pack_sharded`."""
    return P(None, TICKERS_AXIS, None)


def scan_output_spec() -> P:
    """PartitionSpec of the sharded resident scan's ``[N, F, D, T]``
    output: only the trailing tickers axis is sharded, so the single
    consolidated fetch gathers one contiguous block per shard."""
    return P(None, None, None, TICKERS_AXIS)


def put_packed_year(stacked, mesh: Mesh):
    """device_put a host ``[N, S, L]`` stacked packed year onto the
    mesh, shard s to the device owning tickers-shard s. Dispatch is
    async — callers overlap it against in-flight compute (the bench's
    double-buffered group ingest) and never need to block: the
    consuming executable's data dependency orders the transfer."""
    return jax.device_put(stacked, NamedSharding(mesh, packed_year_spec()))


def packed_year_2d_spec() -> P:
    """PartitionSpec for a stacked 2-D packed year ``[N, Sd, St, L]``
    (batches x day-shards x ticker-shards x per-shard packed bytes):
    the day-shard axis maps onto the mesh days axis, the ticker-shard
    axis onto tickers; batches and bytes stay whole. Host-side twin of
    :func:`..data.wire.pack_sharded_2d`."""
    return P(None, DAYS_AXIS, TICKERS_AXIS, None)


def scan_output_2d_spec() -> P:
    """PartitionSpec of the 2-D resident scan's ``[N, F, D, T]``
    output: each batch's day rows shard over the days axis, tickers
    over tickers — device (i, j) holds its own contiguous
    ``[N, F, D/d, T/t]`` block until the consolidated fetch."""
    return P(None, None, DAYS_AXIS, TICKERS_AXIS)


def span_carry_spec() -> P:
    """PartitionSpec of a cross-day carry leaf ``[T]``
    (:func:`..stream.carry.init_span_state`): sharded over tickers,
    replicated over the days axis — the post-handoff placement every
    day-shard agrees on."""
    return P(TICKERS_AXIS)


def put_packed_year_2d(stacked, mesh: Mesh):
    """device_put a host ``[N, Sd, St, L]`` stacked packed year onto a
    2-D ``(days, tickers)`` mesh — shard (i, j)'s bytes land on the
    device owning day-shard i x tickers-shard j. Same async-dispatch
    contract as :func:`put_packed_year` (callers overlap, never
    block)."""
    return jax.device_put(stacked, NamedSharding(mesh,
                                                 packed_year_2d_spec()))


def put_span_carry(carry, mesh: Mesh):
    """device_put a host cross-day carry (``{last_close, n_bars, has}``
    ``[T]`` leaves — ``stream.carry.init_span_state``) onto the mesh:
    sharded over tickers, replicated over days."""
    s = NamedSharding(mesh, span_carry_spec())
    return {k: jax.device_put(v, s) for k, v in carry.items()}


def day_batch_spec(batched: bool = True) -> P:
    """PartitionSpec for ``bars [D, T, 240, 5]`` (or ``[T, 240, 5]``)."""
    if batched:
        return P(DAYS_AXIS, TICKERS_AXIS, None, None)
    return P(TICKERS_AXIS, None, None)


def mask_spec(batched: bool = True) -> P:
    if batched:
        return P(DAYS_AXIS, TICKERS_AXIS, None)
    return P(TICKERS_AXIS, None)


def _pad_to_multiple(a: np.ndarray, mult: int, axis: int) -> np.ndarray:
    rem = a.shape[axis] % mult
    if rem == 0:
        return a
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, mult - rem)
    return np.pad(a, pad)


def shard_day_batch(bars, mask, mesh: Mesh):
    """Place a host day-batch onto the mesh, zero-padding the tickers axis
    to a shard multiple (padding lanes have mask=False so every masked
    reduction ignores them). The padding waste lands in the
    ``mesh.pad_waste_frac{axis=tickers}`` gauge (ISSUE 9) — dead lanes
    cost device time on every shard, and a universe/shard-count change
    that silently doubles them should be visible, not archaeological.

    Returns ``(bars, mask, n_tickers)`` — callers slice results back to
    ``n_tickers``.
    """
    from ..telemetry import get_telemetry

    bars = np.asarray(bars)
    mask = np.asarray(mask)
    batched = bars.ndim == 4
    t_axis = 1 if batched else 0
    n_tickers = bars.shape[t_axis]
    t_shards = mesh.shape[TICKERS_AXIS]
    bars = _pad_to_multiple(bars, t_shards, t_axis)
    mask = _pad_to_multiple(mask, t_shards, t_axis)
    get_telemetry().meshplane.record_pad_waste(
        n_tickers, bars.shape[t_axis], axis="tickers")
    if batched:
        d_shards = mesh.shape[DAYS_AXIS]
        bars = _pad_to_multiple(bars, d_shards, 0)
        mask = _pad_to_multiple(mask, d_shards, 0)
    bars_s = jax.device_put(bars, NamedSharding(mesh, day_batch_spec(batched)))
    mask_s = jax.device_put(mask, NamedSharding(mesh, mask_spec(batched)))
    return bars_s, mask_s, n_tickers
