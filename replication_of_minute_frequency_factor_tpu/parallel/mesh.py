"""Device mesh and sharding layout for the day-batch tensor.

Layout: ``bars [D, T, 240, 5]`` and ``mask [D, T, 240]`` shard over a 2-D
logical mesh ``(days, tickers)``. Factor kernels are pure per-(day, ticker)
maps, so both axes are data-parallel for L1; the per-date cross-sectional
stage (L3) keeps the days axis data-parallel and turns the tickers axis into
a collective axis (see collectives.py).

Replaces reference joblib fan-out (MinuteFrequentFactorCICC.py:85-94): one
process per day-file becomes one mesh coordinate per (day-shard,
ticker-shard), with ICI collectives instead of filesystem round-trips.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DAYS_AXIS = "days"
TICKERS_AXIS = "tickers"


def make_mesh(
    shape: Optional[Tuple[int, int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ``(days, tickers)`` mesh over the available devices.

    Default shape ``(1, n_devices)``: the ticker axis is the wide one
    (~5000 tickers vs. a handful of days per batch) and per-stock kernels
    need zero communication, so all ICI bandwidth is reserved for the small
    cross-sectional collectives.
    """
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    if shape is None:
        shape = (1, devices.size)
    if shape[0] * shape[1] != devices.size:
        raise ValueError(
            f"mesh shape {shape} does not match {devices.size} devices")
    return Mesh(devices.reshape(shape), (DAYS_AXIS, TICKERS_AXIS))


def day_batch_spec(batched: bool = True) -> P:
    """PartitionSpec for ``bars [D, T, 240, 5]`` (or ``[T, 240, 5]``)."""
    if batched:
        return P(DAYS_AXIS, TICKERS_AXIS, None, None)
    return P(TICKERS_AXIS, None, None)


def mask_spec(batched: bool = True) -> P:
    if batched:
        return P(DAYS_AXIS, TICKERS_AXIS, None)
    return P(TICKERS_AXIS, None)


def _pad_to_multiple(a: np.ndarray, mult: int, axis: int) -> np.ndarray:
    rem = a.shape[axis] % mult
    if rem == 0:
        return a
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, mult - rem)
    return np.pad(a, pad)


def shard_day_batch(bars, mask, mesh: Mesh):
    """Place a host day-batch onto the mesh, zero-padding the tickers axis
    to a shard multiple (padding lanes have mask=False so every masked
    reduction ignores them).

    Returns ``(bars, mask, n_tickers)`` — callers slice results back to
    ``n_tickers``.
    """
    bars = np.asarray(bars)
    mask = np.asarray(mask)
    batched = bars.ndim == 4
    t_axis = 1 if batched else 0
    n_tickers = bars.shape[t_axis]
    t_shards = mesh.shape[TICKERS_AXIS]
    bars = _pad_to_multiple(bars, t_shards, t_axis)
    mask = _pad_to_multiple(mask, t_shards, t_axis)
    if batched:
        d_shards = mesh.shape[DAYS_AXIS]
        bars = _pad_to_multiple(bars, d_shards, 0)
        mask = _pad_to_multiple(mask, d_shards, 0)
    bars_s = jax.device_put(bars, NamedSharding(mesh, day_batch_spec(batched)))
    mask_s = jax.device_put(mask, NamedSharding(mesh, mask_spec(batched)))
    return bars_s, mask_s, n_tickers
