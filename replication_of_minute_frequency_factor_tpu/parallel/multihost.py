"""Multi-host (DCN) scaffolding.

The reference has no multi-node story (its "communication backend" is the
filesystem, SURVEY.md §5); here scale-out past one host is the standard JAX
recipe: ``jax.distributed.initialize`` on every process, one global
``(days, tickers)`` mesh spanning all hosts' devices, and
``make_array_from_process_local_data`` so each host feeds only its own
shard of the day batch — factor compute stays collective-free, the small
evaluation collectives ride ICI within a host and DCN across.

On a single process these helpers degrade to the local mesh path (tested);
on a pod slice, launch one process per host with the usual coordinator
environment and call :func:`initialize` first.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding

from ..telemetry import get_telemetry
from .mesh import day_batch_spec, mask_spec, make_mesh


def _is_initialized() -> bool:
    """Whether the distributed runtime is already up.

    ``jax.distributed.is_initialized`` only exists on jax >= 0.5 (the
    pinned 0.4.37 exposes just ``initialize``/``shutdown`` — graftlint
    rule GL-A1 class); fall back to the runtime's own client handle,
    which is what ``is_initialized`` reads on newer jax anyway."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src import distributed as _impl
        return getattr(_impl.global_state, "client", None) is not None
    except Exception:  # noqa: BLE001 — treat an unknown runtime as down
        return False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """``jax.distributed.initialize`` with explicit or env-provided
    topology. No-op when the runtime is already initialised or when
    running single-process with no coordinator configured.

    Must run before anything touches the XLA backend —
    ``jax.process_count()`` would itself initialise it, so the
    already-initialised check uses :func:`_is_initialized`.
    Errors are only swallowed on the implicit (env-discovery) path; a
    caller who names a coordinator gets the failure raised."""
    if _is_initialized():
        return
    # spanned: on a pod slice this blocks until every process dials the
    # coordinator, so its duration IS the cross-host startup skew
    tel = get_telemetry()
    with tel.span("multihost.initialize"):
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
        except (ValueError, RuntimeError):
            if coordinator_address is not None:
                raise
            # single-process run without a coordinator: local devices only
            pass
    # topology gauges (ISSUE 9): the pod aggregation's sanity anchors —
    # every merged host bundle must agree on process_count, and each
    # bundle's own index must match its schema-v3 identity stamps
    try:
        tel.gauge("multihost.process_index", jax.process_index())
        tel.gauge("multihost.process_count", jax.process_count())
    except Exception:  # noqa: BLE001 — telemetry must not fail startup
        pass


def global_mesh(shape: Optional[Tuple[int, int]] = None):
    """Mesh over every device of every process (days x tickers)."""
    return make_mesh(shape, devices=jax.devices())


def shard_from_host_local(bars: np.ndarray, mask: np.ndarray, mesh):
    """Build global device arrays from *this host's* slice of the batch.

    Each process passes the rows of the tickers axis it owns (the global
    tickers axis is the concatenation over processes in process order);
    returns globally-sharded ``(bars, mask)`` without any host ever
    materialising the full batch — the multi-host equivalent of
    :func:`..parallel.mesh.shard_day_batch`.
    """
    batched = bars.ndim == 4
    tel = get_telemetry()
    try:
        host = str(jax.process_index())
    except Exception:  # noqa: BLE001 — labeling must not fail the shard
        host = "?"
    with tel.span("multihost.shard_from_host_local"):
        out = (
            jax.make_array_from_process_local_data(
                NamedSharding(mesh, day_batch_spec(batched)), bars),
            jax.make_array_from_process_local_data(
                NamedSharding(mesh, mask_spec(batched)), mask),
        )
    tel.counter("multihost.shards_built", host=host)
    # shard-balance occupancy at the multihost ingest boundary (ISSUE
    # 9): the fraction of this host's lanes that are real bars — a
    # host feeding mostly-masked filler shows up in the pod skew view
    # (``mask`` is the caller's HOST array; no device sync here)
    try:
        tel.meshplane.record_occupancy(float(mask.mean()),
                                       boundary="multihost.ingest")
    except Exception:  # noqa: BLE001 — observation must not fail ingest
        pass
    return out
