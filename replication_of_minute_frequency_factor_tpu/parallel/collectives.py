"""Cross-sectional collectives over a sharded ticker axis.

The only operations in the whole framework that need inter-device
communication are the per-date cross-sectional statistics of evaluation
(Factor.py:172-182 Pearson/Spearman IC; :284-292 quantile cuts). Everything
else — all 58 kernels — is per-(ticker, day) pure and runs with zero
collectives.

Two usage styles:

* moment-style stats (mean/std/corr) as ``psum`` of local partial sums —
  O(1) words over ICI per date;
* order statistics (rank, quantile cut) by ``all_gather`` of the ``[T]``
  cross-section (tiny: 5000 f32 = 20 KB/date) followed by a local sort,
  slicing this shard's lanes back out (SURVEY.md §7 hard-part 5).

Functions suffixed ``_local`` are the per-shard bodies (usable inside any
``shard_map``); the unsuffixed wrappers apply ``shard_map`` over a mesh for
``[dates, tickers]`` matrices sharded ``P(None, 'tickers')``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.registry import compute_factors
from ..ops import rank_average
from ..telemetry import get_telemetry
from .mesh import DAYS_AXIS, TICKERS_AXIS, day_batch_spec, mask_spec


# --------------------------------------------------------------------------
# psum-based masked moments (inside shard_map)
# --------------------------------------------------------------------------

# plain Python scalars, not jnp arrays: building an Array here would commit
# the default backend at import time (ops/masked.py does the same)
_NAN = jnp.nan
_NO_LANE = 2**30  # "no valid lane on this shard" index sentinel


def _count_mean_many(arrays, mask, axis_name):
    """Global count + per-array masked means over the sharded last axis,
    as ``(n, mean_0, mean_1, ...)``; means NaN if n=0.

    The count rides the same fused tuple psum as every sum (one
    all-reduce total); it is carried in f32, exact for any count below
    2^24 lanes.
    """
    n, *sums = jax.lax.psum(
        (jnp.sum(mask, axis=-1, dtype=jnp.float32),)
        + tuple(jnp.sum(jnp.where(mask, a, 0.0), axis=-1) for a in arrays),
        axis_name)
    nn = jnp.maximum(n, 1)
    return (n,) + tuple(jnp.where(n > 0, s / nn, _NAN) for s in sums)


def _count_mean(x, mask, axis_name):
    return _count_mean_many((x,), mask, axis_name)


def _first_valid_many(arrays, mask, axis_name):
    """Values at the globally-first valid lane of the sharded cross-section
    (NaN if none), for several arrays sharing one mask. Mirrors
    ``ops.masked.masked_first`` under sharding: each shard offers its first
    valid *global* column index, ``pmin`` picks the winner, and one psum of
    the one-hot-selected values broadcasts them — the index machinery and
    collectives are shared across the arrays (one pmin + one fused psum),
    which matters on the ICI-bound per-date eval path."""
    t_local = mask.shape[-1]
    shard = jax.lax.axis_index(axis_name)
    gcol = jnp.arange(t_local, dtype=jnp.int32) + shard * t_local
    gidx = jnp.where(mask, gcol, _NO_LANE)
    gmin = jax.lax.pmin(jnp.min(gidx, axis=-1), axis_name)
    here = gidx == gmin[..., None]
    vals = jax.lax.psum(
        tuple(jnp.sum(jnp.where(here, a, 0.0), axis=-1) for a in arrays),
        axis_name)
    has = gmin < _NO_LANE
    return tuple(jnp.where(has, v, _NAN) for v in vals)


def xs_masked_mean_local(x, mask, axis_name=TICKERS_AXIS):
    _, mean = _count_mean(x, mask, axis_name)
    return mean


def xs_masked_std_local(x, mask, axis_name=TICKERS_AXIS, ddof: int = 1):
    """Cross-device masked std, polars default ddof=1 (SURVEY.md Q11).

    Two-pass like ``ops.masked.masked_std`` (psum mean, then psum of squared
    deviations): the one-pass ``ss - n*mean^2`` form leaks f32 cancellation
    noise (~1e-4 relative) on near-constant cross-sections and returns
    0/inf instead of NaN when ``n <= ddof``.
    """
    n, mean = _count_mean(x, mask, axis_name)
    d = jnp.where(mask, x - mean[..., None], 0.0)
    m2 = jax.lax.psum(jnp.sum(d * d, axis=-1), axis_name)
    var = jnp.where(n > ddof, m2 / jnp.maximum(n - ddof, 1), _NAN)
    return jnp.sqrt(var)


def xs_pearson_local(x, y, mask, axis_name=TICKERS_AXIS):
    """Masked Pearson correlation across the sharded axis (per leading row).

    The per-date IC of Factor.py:172-177 under ticker sharding. Mirrors
    ``ops.masked.masked_corr``: both series anchored to their globally-first
    valid value (shift-invariant; makes constant series yield exactly-zero
    variance, hence NaN as polars), then two-pass moments via psum.
    """
    ax, ay = _first_valid_many((x, y), mask, axis_name)
    x = x - ax[..., None]
    y = y - ay[..., None]
    n, mx, my = _count_mean_many((x, y), mask, axis_name)
    dx = jnp.where(mask, x - mx[..., None], 0.0)
    dy = jnp.where(mask, y - my[..., None], 0.0)
    cov, vx, vy = jax.lax.psum(
        (jnp.sum(dx * dy, axis=-1), jnp.sum(dx * dx, axis=-1),
         jnp.sum(dy * dy, axis=-1)), axis_name)
    r = cov / jnp.sqrt(vx * vy)  # zero variance -> NaN, as polars
    return jnp.where(n > 1, r, _NAN)


def xs_rank_local(x, mask, axis_name=TICKERS_AXIS):
    """Average-tie rank among valid lanes of the full cross-section.

    all_gather the [rows, T_local] block from every shard, rank the global
    [rows, T] matrix locally (identical on all shards), then slice this
    shard's columns back out.
    """
    full_x = jax.lax.all_gather(x, axis_name, axis=-1, tiled=True)
    full_m = jax.lax.all_gather(mask, axis_name, axis=-1, tiled=True)
    r = rank_average(full_x, full_m)
    idx = jax.lax.axis_index(axis_name)
    return jax.lax.dynamic_slice_in_dim(
        r, idx * x.shape[-1], x.shape[-1], axis=-1)


def xs_global_rank_local(x, mask, axis_name=TICKERS_AXIS):
    """Average-tie rank of a FLATTENED sharded frame — the sharded twin
    of ``DayContext.eod_ret_global_rank`` (the ``doc_pdf*`` family's
    whole-day-frame rank, the ONE cross-ticker intermediate in the 58
    kernels).

    ``x``/``mask`` are ``[..., T_local * 240]`` — the local tickers
    block flattened ticker-major, so the tiled ``all_gather`` along the
    last axis reassembles exactly the single-device flatten order
    (shard s's block lands at columns ``[s * cols_local, (s+1) *
    cols_local)``). The gathered frame is ranked locally — bitwise the
    single-device computation, since every shard ranks the identical
    full frame — and this shard's lanes are sliced back out. Same
    gather-compute-slice shape as :func:`xs_rank_local`, kept separate
    because the resident scan calls it per scan step on a frame, not on
    a ``[dates, tickers]`` matrix."""
    full_x = jax.lax.all_gather(x, axis_name, axis=-1, tiled=True)
    full_m = jax.lax.all_gather(mask, axis_name, axis=-1, tiled=True)
    r = rank_average(full_x, full_m)
    idx = jax.lax.axis_index(axis_name)
    return jax.lax.dynamic_slice_in_dim(
        r, idx * x.shape[-1], x.shape[-1], axis=-1)


def xs_qcut_local(x, mask, group_num: int, axis_name=TICKERS_AXIS):
    """Per-date quantile-bucket labels over a SHARDED cross-section
    (group_test's qcut, Factor.py:284-292, under tickers-axis sharding —
    SURVEY.md §7 hard-part 5).

    Same shape as ranking: all_gather the tiny [rows, T] cross-section
    (5000 f32 = 20 KB/date), run the production single-device qcut core
    on the gathered matrix — REUSED, not reimplemented, so sharded and
    local labels cannot drift — and slice this shard's lanes back out.
    """
    from .. import eval_ops

    full_x = jax.lax.all_gather(x, axis_name, axis=-1, tiled=True)
    full_m = jax.lax.all_gather(mask, axis_name, axis=-1, tiled=True)
    lab = eval_ops._qcut_labels_jit(full_x, full_m, group_num)
    idx = jax.lax.axis_index(axis_name)
    return jax.lax.dynamic_slice_in_dim(
        lab, idx * x.shape[-1], x.shape[-1], axis=-1)


def xs_population_topk_local(stats_local, k: int, n_pop: int,
                             axis_name=TICKERS_AXIS):
    """End-of-generation top-k gather for the population-sharded
    discovery loop (ISSUE 14) — the ONE collective of
    ``research/fitness.generation_fitness_sharded``.

    ``stats_local [P_local, 4]`` is this shard's slice of the
    generation's stats matrix (column 0 = the selection fitness).
    One tiled ``all_gather`` along the population axis reassembles the
    global ``[P_pad, 4]`` matrix in shard order — exactly the
    single-device layout, since the host sharded the genome matrix
    contiguously — then every shard computes the identical top-k
    locally (the gather-compute shape of :func:`xs_global_rank_local`:
    the gathered frame is tiny, ``P x 4`` f32). Rows at or past
    ``n_pop`` are shard-multiple padding and are masked to -inf before
    the top-k (a padding genome must never be selected); NaN fitness
    ranks below every finite candidate, matching host selection's
    ``nan_to_num(-1)``. Returns ``(stats [P_pad, 4], top_vals [k],
    top_idx [k])``, replicated.

    Host-side dispatch counting lives with the caller
    (``mesh.collective_dispatches{label=discover_topk}`` via
    ``research/evolve.py``), exactly like the ``_xs_wrap``
    collectives."""
    full = jax.lax.all_gather(stats_local, axis_name, axis=0, tiled=True)
    fit = jnp.nan_to_num(full[:, 0], nan=-1.0)
    fit = jnp.where(jnp.arange(fit.shape[0]) < n_pop, fit, -jnp.inf)
    top_vals, top_idx = jax.lax.top_k(fit, k)
    return full, top_vals, top_idx


def xs_carry_handoff_local(state, combine, axis_name=DAYS_AXIS,
                           axis_size: int = 1):
    """Cross-day carry handoff between day-shards (ISSUE 13): combine
    each shard's end-of-span state into the global prefix state,
    replicated across the ``d`` axis, through explicit
    ``lax.ppermute`` legs — the 2-D resident scan's ONE days-axis
    collective (``xs_global_rank_local`` stays the only cross-TICKER
    one).

    ``combine(a, b)`` must be associative, commutative and IDEMPOTENT
    (``stream.carry.combine_span_state`` is, by max-over-distinct-day
    construction): the handoff runs ``ceil(log2(d))`` doubling rounds
    of ring-shifted ppermutes, which revisit shards when ``d`` is not
    a power of two. On a 1-extent day axis the leg degenerates to one
    identity permute — emitted anyway, so the reserved
    ``__resident_scan_2d__`` wrapper's jaxpr fingerprint always
    carries the collective class (analysis/jaxpr_tier.py traces on a
    one-device mesh).

    Host-side dispatch counting lives with the caller
    (``mesh.collective_dispatches{label=carry_handoff}`` via
    ``pipeline.compute_packed_resident_2d``), exactly like the
    ``_xs_wrap`` collectives.
    """
    shifts, s = [], 1
    while s < axis_size:
        shifts.append(s)
        s *= 2
    if not shifts:
        shifts = [0]  # identity leg: keep the primitive in the jaxpr
    for shift in shifts:
        perm = [(i, (i + shift) % axis_size) for i in range(axis_size)]
        recv = jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), state)
        state = combine(state, recv)
    return state


# --------------------------------------------------------------------------
# shard_map wrappers for [dates, tickers] matrices
# --------------------------------------------------------------------------

def _xs_wrap(body, label: str):
    """Wrap a local body into a jitted shard_map over P(None, 'tickers').

    The outer (non-jit) wrapper spans the dispatch as
    ``collective.<label>`` with an EXPLICIT ``kind=host_dispatch``
    label (ISSUE 9): JAX dispatch is async, so this span is host-side
    time to trace/launch the collective graph, NOT on-device
    collective time — the label rides the span's Perfetto args and its
    JSONL record, so the two can no longer be conflated in a trace
    view. On-device collective seconds live in the attribution
    post-processor's ``device.collective_time_s`` block
    (``telemetry.attribution.collective_breakdown``), built from a
    profiler capture's device pids. Each dispatch also counts in
    ``mesh.collective_dispatches{label=}`` (telemetry/meshplane.py)."""

    @functools.partial(jax.jit, static_argnames=("mesh",))
    def run_jit(mesh: Mesh, *arrays):
        spec = P(None, TICKERS_AXIS)
        fn = shard_map(
            body, mesh=mesh,
            in_specs=(spec,) * len(arrays),
            out_specs=body.out_spec,
        )
        return fn(*arrays)

    def run(mesh: Mesh, *arrays):
        tel = get_telemetry()
        tel.meshplane.note_collective(label)
        with tel.tracer(f"collective.{label}", kind="host_dispatch"):
            return run_jit(mesh, *arrays)

    run.jitted = run_jit
    return run


def _mean_body(x, m):
    return xs_masked_mean_local(x, m)


_mean_body.out_spec = P(None)


def _std_body(x, m):
    return xs_masked_std_local(x, m)


_std_body.out_spec = P(None)


def _pearson_body(x, y, m):
    return xs_pearson_local(x, y, m)


_pearson_body.out_spec = P(None)


def _rank_body(x, m):
    return xs_rank_local(x, m)


_rank_body.out_spec = P(None, TICKERS_AXIS)


xs_masked_mean = _xs_wrap(_mean_body, "xs_masked_mean")
xs_masked_std = _xs_wrap(_std_body, "xs_masked_std")
xs_pearson = _xs_wrap(_pearson_body, "xs_pearson")
xs_rank = _xs_wrap(_rank_body, "xs_rank")


@functools.partial(jax.jit, static_argnames=("mesh", "group_num"))
def _xs_qcut_jit(mesh: Mesh, x, m, group_num: int = 5):
    spec = P(None, TICKERS_AXIS)
    fn = shard_map(
        lambda a, b: xs_qcut_local(a, b, group_num),
        mesh=mesh, in_specs=(spec, spec), out_specs=spec)
    return fn(x, m)


def xs_qcut(mesh: Mesh, x, m, group_num: int = 5):
    """Sharded per-date quantile-bucket labels (see xs_qcut_local).
    Same host-dispatch span semantics as :func:`_xs_wrap`."""
    tel = get_telemetry()
    tel.meshplane.note_collective("xs_qcut")
    with tel.tracer("collective.xs_qcut", kind="host_dispatch"):
        return _xs_qcut_jit(mesh, x, m, group_num)


# --------------------------------------------------------------------------
# sharded factor computation
# --------------------------------------------------------------------------

@functools.lru_cache(maxsize=32)
def _sharded_fn(mesh: Mesh, batched: bool, names, replicate_quirks: bool,
                rolling_impl: str):
    out_spec = P(*day_batch_spec(batched)[:2]) if batched else P(TICKERS_AXIS)
    return jax.jit(
        functools.partial(
            compute_factors, names=names, replicate_quirks=replicate_quirks,
            rolling_impl=rolling_impl),
        in_shardings=(NamedSharding(mesh, day_batch_spec(batched)),
                      NamedSharding(mesh, mask_spec(batched))),
        out_shardings=NamedSharding(mesh, out_spec),
    )


def sharded_compute_factors(
    bars, mask, mesh: Mesh,
    names: Optional[Tuple[str, ...]] = None,
    replicate_quirks: bool = True,
    rolling_impl: Optional[str] = None,
):
    """All 58 kernels over a mesh-sharded day batch.

    Inputs follow :func:`..parallel.mesh.shard_day_batch` placement; outputs
    are ``{name: [D, T]}`` sharded ``P('days', 'tickers')``. The graph
    contains no collectives — XLA compiles one fully data-parallel module.
    The jitted wrapper caches per (mesh, shape-kind, names, quirks,
    rolling_impl), and a None ``rolling_impl`` resolves the config value
    here so the backend choice is always part of that key.
    """
    if rolling_impl is None:
        from ..config import get_config
        rolling_impl = get_config().rolling_impl
    fn = _sharded_fn(mesh, bars.ndim == 4, names, replicate_quirks,
                     rolling_impl)
    tel = get_telemetry()
    tel.counter("collective.sharded_factor_batches")
    with tel.tracer("collective.sharded_factors", kind="host_dispatch"):
        return fn(bars, mask)
