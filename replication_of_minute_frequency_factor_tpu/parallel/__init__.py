"""Distributed execution: device meshes, shardings, and collectives.

The reference's only parallelism is a joblib process pool over trading-day
files (MinuteFrequentFactorCICC.py:85-94) with the filesystem as its
"communication backend". Here the equivalent is first-class (SURVEY.md §5):

* a ``jax.sharding.Mesh`` over ``(days, tickers)`` logical axes;
* ``NamedSharding`` placement of the day-batch tensor so per-stock kernels
  run with zero communication (tickers axis is embarrassingly parallel);
* explicit XLA collectives (``psum`` / ``all_gather`` over ICI) via
  ``shard_map`` for the only genuinely cross-ticker ops: per-date
  cross-sectional moments, ranks and quantile cuts used by evaluation.
"""

from .mesh import (
    DAYS_AXIS,
    TICKERS_AXIS,
    day_batch_spec,
    make_mesh,
    mask_spec,
    packed_year_2d_spec,
    packed_year_spec,
    put_packed_year,
    put_packed_year_2d,
    put_span_carry,
    resident_mesh,
    scan_output_2d_spec,
    scan_output_spec,
    shard_day_batch,
    span_carry_spec,
)
from .collectives import (
    sharded_compute_factors,
    xs_carry_handoff_local,
    xs_global_rank_local,
    xs_masked_mean,
    xs_masked_std,
    xs_pearson,
    xs_qcut,
    xs_rank,
)

__all__ = [
    "DAYS_AXIS",
    "TICKERS_AXIS",
    "make_mesh",
    "day_batch_spec",
    "mask_spec",
    "packed_year_spec",
    "packed_year_2d_spec",
    "put_packed_year",
    "put_packed_year_2d",
    "put_span_carry",
    "resident_mesh",
    "scan_output_spec",
    "scan_output_2d_spec",
    "span_carry_spec",
    "shard_day_batch",
    "xs_carry_handoff_local",
    "xs_global_rank_local",
    "sharded_compute_factors",
    "xs_masked_mean",
    "xs_masked_std",
    "xs_pearson",
    "xs_qcut",
    "xs_rank",
]
