"""Structured logging + failure reporting.

The reference's observability is a bare ``print`` on worker error and a tqdm
bar (MinuteFrequentFactorCICC.py:24,93). Here failures aggregate into a
structured report attached to pipeline results so a batch run can be audited
after the fact (SURVEY.md §5 failure detection).
"""

from __future__ import annotations

import dataclasses
import logging
import traceback
from typing import List

_CONFIGURED = False


def get_logger(name: str) -> logging.Logger:
    global _CONFIGURED
    if not _CONFIGURED:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
        root = logging.getLogger("replication_of_minute_frequency_factor_tpu")
        if not root.handlers:
            root.addHandler(h)
            root.setLevel(logging.INFO)
        _CONFIGURED = True
    return logging.getLogger(name)


@dataclasses.dataclass
class Failure:
    key: str          # e.g. the trading date
    source: str       # e.g. the file path
    error: str
    trace: str


class FailureReport:
    """Per-task failure isolation ledger (reference: caught-and-printed
    exceptions silently dropped the day, MinuteFrequentFactorCICC.py:20-25)."""

    def __init__(self):
        self.failures: List[Failure] = []

    def record(self, key: str, source: str, exc: BaseException) -> None:
        self.failures.append(Failure(
            key=key, source=source, error=f"{type(exc).__name__}: {exc}",
            trace=traceback.format_exc()))

    def __len__(self) -> int:
        return len(self.failures)

    def __bool__(self) -> bool:
        return bool(self.failures)

    def keys(self) -> List[str]:
        return [f.key for f in self.failures]

    def summary(self) -> str:
        if not self.failures:
            return "no failures"
        lines = [f"{len(self.failures)} failed:"]
        lines += [f"  {f.key} ({f.source}): {f.error}" for f in self.failures]
        return "\n".join(lines)

    def save(self, path: str, carried=()) -> None:
        """Write the ledger as JSON (one record per failed day) so a
        skipped day is inspectable after the run, not just a log line.

        ``carried`` are prior-ledger records (dicts) for days this run
        did NOT reattempt — they are still lost and must stay on the
        ledger, or a later clean run would erase the only pointer
        ``--retry-failed`` has to them."""
        import json
        with open(path, "w") as fh:
            json.dump(list(carried)
                      + [{"key": f.key, "source": f.source,
                          "error": f.error, "trace": f.trace}
                         for f in self.failures], fh, indent=1)
