from .logging import FailureReport, get_logger
from .tracing import Timer, trace_annotation

__all__ = ["FailureReport", "get_logger", "Timer", "trace_annotation"]
