"""Debug-mode input validation (SURVEY.md §5 race-detection/sanitizers).

Races can't occur by construction (pure jit kernels), so the useful
sanitizer is *data* validation: a day tensor whose valid lanes carry NaN
prices, negative volume, or inverted high/low silently corrupts every
downstream factor. ``validate_batch`` is the ``jax.debug``-style guard the
pipeline runs when ``Config.debug_validate`` is on.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..data.minute import F_CLOSE, F_HIGH, F_LOW, F_OPEN, F_VOLUME


class DayDataError(ValueError):
    pass


def validate_batch(bars: np.ndarray, mask: np.ndarray,
                   raise_: bool = True) -> List[str]:
    """Check invariants of a ``[..., T, 240, 5]`` day batch on valid lanes.

    Returns a list of violation descriptions (empty = clean); raises
    ``DayDataError`` with the full list when ``raise_``.
    """
    bars = np.asarray(bars)
    mask = np.asarray(mask)
    problems: List[str] = []
    v = bars[mask]  # [n_valid, 5]
    if not np.isfinite(v).all():
        n = int((~np.isfinite(v)).any(axis=-1).sum())
        problems.append(f"{n} valid bars carry non-finite fields")
    prices = v[:, [F_OPEN, F_HIGH, F_LOW, F_CLOSE]]
    if (prices <= 0).any():
        n = int((prices <= 0).any(axis=-1).sum())
        problems.append(f"{n} valid bars have non-positive prices")
    if (v[:, F_VOLUME] < 0).any():
        problems.append(
            f"{int((v[:, F_VOLUME] < 0).sum())} valid bars have "
            "negative volume")
    hl = v[:, F_HIGH] < v[:, F_LOW]
    if hl.any():
        problems.append(f"{int(hl.sum())} valid bars have high < low")
    if problems and raise_:
        raise DayDataError("; ".join(problems))
    return problems
