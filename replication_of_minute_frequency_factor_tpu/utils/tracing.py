"""Timing and XLA-level tracing hooks (SURVEY.md §5 tracing/profiling).

``Timer`` wraps host-side stages (IO, gridding, device step);
``trace_annotation`` tags regions so they show up named in a
``jax.profiler`` trace when one is being captured.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List


class Timer:
    """Accumulating named stage timer. Thread-safe: the pipeline's
    producer thread and the consumer's per-day isolation path time the
    same stage names concurrently, and an unlocked read-modify-write
    would drop increments.

    >>> t = Timer()
    >>> with t("io"): ...
    >>> t.totals()["io"]
    """

    def __init__(self):
        self._totals: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def __call__(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._totals[name] = self._totals.get(name, 0.0) + dt
                self._counts[name] = self._counts.get(name, 0) + 1

    def totals(self) -> Dict[str, float]:
        return dict(self._totals)

    def report(self) -> str:
        rows: List[str] = []
        for k in sorted(self._totals, key=self._totals.get, reverse=True):
            rows.append(f"{k}: {self._totals[k]:.3f}s x{self._counts[k]}")
        return "; ".join(rows) or "no timings"


@contextlib.contextmanager
def trace_annotation(name: str):
    """Named region in the XLA profiler timeline (no-op overhead outside a
    capture)."""
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield
