"""Tier C: the concurrency rule engine (rules GL-C1..GL-C4).

The threaded layers (``serve/``, ``fleet/``, ``stream/``,
``research/``, ``telemetry/``) declare their lock discipline next to
the classes that own it — a module-level ``GLC_CONTRACT`` literal,
mirroring ``GLA3_BOUNDARY_SYNCS``: per class, which lock guards which
thread-shared attributes. This tier machine-checks the declarations on
the AST; ``telemetry/lockcheck.py`` is the runtime twin that asserts
the same contract at mutation time under ``MFF_LOCK_ASSERT=1``.

Contract shape (parsed with ``ast.literal_eval`` — literals only)::

    GLC_CONTRACT = {
        "MetricsRegistry": {
            "lock": "_lock",
            "guards": ("_counters", "_gauges", "_hists"),
            "init": (),        # extra single-threaded methods
            "locked": (),      # caller-holds-lock helpers
        },
    }

``__init__`` is always construction-time single-threaded; ``init``
lists further methods documented as running before any thread starts.
``locked`` lists private helpers whose documented contract is "caller
holds the lock" (e.g. ``ShedPolicy._demote``) — they skip the GL-C1
same-class check but stay covered by the runtime twin, which checks
the lock is actually held whenever they run.

Rule catalog (docs/static-analysis.md):

GL-C1  a write / read-modify-write of a declared guarded attribute
       outside a ``with self.<lock>:`` scope. Lock-scope inference is
       lexical containment in the ``with`` body, which is exactly
       right for early returns and try/finally: the ``with`` statement
       guarantees the lock is held for every statement of its suite
       and released on every exit path. A nested ``def``/``lambda``
       resets the inference — closures run later, when the lock is no
       longer held. Second arm: reaching through an object attribute
       into ANOTHER object's guarded internals
       (``self.router._inflight``) flags read or write — cross-object
       access must go through a locked accessor on the owner.
GL-C2  every ``threading.Thread`` started in the scanned layers must
       be ``daemon=True``, must have a stop/join path (a ``.join``
       somewhere in the owning class/module, or the thread object is
       returned to the caller, who owns its lifecycle), and its target
       must not mutate guarded state of a foreign class through a bare
       reference.
GL-C3  file outputs from methods of a contract-declaring class (the
       threaded contexts: flight dumps, timeline/bench records) must
       use the write-then-``os.replace`` atomic idiom so a reader
       never sees a half-written file. ``__init__``/``init`` methods
       are exempt (opening an append-mode sink once at construction is
       not a threaded write).
GL-C4  no bare ``except: pass`` swallowing inside a thread target —
       a daemon loop that eats exceptions silently turns a real bug
       into a stalled sampler; count a telemetry counter instead (the
       ``MeshPlane.measure_ready`` / FlightRecorder discipline).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Tuple

from .violations import Violation

#: layers the tier scans. ``concurrency`` is the fixture pseudo-layer:
#: tests/fixtures/graftlint/concurrency/ scans under that directory
#: name so Tier A's layer-scoped rules stay silent on the fixtures.
CONCURRENCY_SCOPE = ("serve", "fleet", "stream", "research",
                     "telemetry", "concurrency")

#: the module-level declaration name the tier looks for
CONTRACT_NAME = "GLC_CONTRACT"

#: method names that mutate their receiver in place (GL-C1/GL-C2)
MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear",
    "update", "setdefault", "rotate", "sort", "reverse",
})


# --------------------------------------------------------------------------
# contract collection (pass 1)
# --------------------------------------------------------------------------


def _load_contract(node: ast.Assign) -> Optional[dict]:
    """The ``GLC_CONTRACT = {...}`` literal, or None if not one."""
    if len(node.targets) != 1:
        return None
    t = node.targets[0]
    if not (isinstance(t, ast.Name) and t.id == CONTRACT_NAME):
        return None
    try:
        value = ast.literal_eval(node.value)
    except (ValueError, SyntaxError):
        return {}
    return value if isinstance(value, dict) else {}


def _contract_errors(contract: dict) -> List[str]:
    errs = []
    for cls, spec in contract.items():
        if not isinstance(spec, dict) or not isinstance(
                spec.get("lock"), str):
            errs.append(f"{cls}: spec must be a dict with a str 'lock'")
            continue
        for key in ("guards", "init", "locked"):
            val = spec.get(key, ())
            if not (isinstance(val, (tuple, list))
                    and all(isinstance(a, str) for a in val)):
                errs.append(f"{cls}: {key!r} must be a tuple of str")
    return errs


class _FileScan:
    """One parsed module: tree, declared contracts, violations."""

    def __init__(self, file_path: str, display_path: str,
                 scope_parts: Tuple[str, ...]):
        self.file_path = file_path
        self.path = display_path
        self.scope_parts = scope_parts
        with open(file_path, "rb") as fh:
            self.tree = ast.parse(fh.read(), filename=file_path)
        self.violations: List[Violation] = []
        self.contracts: Dict[str, dict] = {}
        self.threading_names: Dict[str, str] = {}
        for node in self.tree.body:
            if isinstance(node, ast.Assign):
                c = _load_contract(node)
                if c is not None:
                    for err in _contract_errors(c):
                        self.add("GL-C1", node, CONTRACT_NAME,
                                 f"malformed concurrency contract — {err}")
                    self.contracts.update(
                        {k: v for k, v in c.items()
                         if isinstance(v, dict)
                         and isinstance(v.get("lock"), str)})
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "threading":
                        self.threading_names[a.asname or "threading"] \
                            = "threading"
            elif isinstance(node, ast.ImportFrom) \
                    and node.module == "threading" and node.level == 0:
                for a in node.names:
                    self.threading_names[a.asname or a.name] = a.name

    def in_scope(self) -> bool:
        return bool(set(self.scope_parts[:-1]) & set(CONCURRENCY_SCOPE))

    def add(self, code: str, node: ast.AST, symbol: str,
            message: str) -> None:
        self.violations.append(Violation(
            code=code, path=self.path,
            line=getattr(node, "lineno", 0), symbol=symbol,
            message=message))


# --------------------------------------------------------------------------
# small AST helpers
# --------------------------------------------------------------------------


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.x`` -> 'x'; None otherwise."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _is_lock_with(node: ast.With, lock: str) -> bool:
    """Does any withitem acquire ``self.<lock>``?"""
    for item in node.items:
        if _self_attr(item.context_expr) == lock:
            return True
    return False


def _class_methods(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {m.name: m for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _mutation_receivers(node: ast.AST):
    """Yield (receiver_expr, attr, kind) for every in-place mutation
    expressed by ``node``: attribute rebinds, subscript stores/deletes,
    augmented assigns, and mutator-method calls. The receiver is the
    expression owning the attribute (``self`` in ``self._ring.append``).
    """
    def targets_of(t: ast.AST):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from targets_of(e)
        elif isinstance(t, ast.Starred):
            yield from targets_of(t.value)
        elif isinstance(t, ast.Attribute):
            yield (t.value, t.attr, "rebind")
        elif isinstance(t, ast.Subscript) \
                and isinstance(t.value, ast.Attribute):
            yield (t.value.value, t.value.attr, "store")

    if isinstance(node, ast.Assign):
        for t in node.targets:
            yield from targets_of(t)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        if not (isinstance(node, ast.AnnAssign) and node.value is None):
            yield from targets_of(node.target)
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            yield from targets_of(t)
    elif isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr in MUTATORS \
            and isinstance(node.func.value, ast.Attribute):
        yield (node.func.value.value, node.func.value.attr, "mutate")


# --------------------------------------------------------------------------
# GL-C1: lock discipline
# --------------------------------------------------------------------------


def _check_c1_class(scan: _FileScan, cls: ast.ClassDef,
                    contract: dict) -> None:
    lock = contract["lock"]
    guards = set(contract.get("guards", ()))
    exempt = ({"__init__"} | set(contract.get("init", ()))
              | set(contract.get("locked", ())))
    methods = _class_methods(cls)
    for name in sorted(set(contract.get("init", ()))
                       | set(contract.get("locked", ()))):
        if name not in methods:
            scan.add("GL-C1", cls, f"{cls.name}.{name}",
                     f"contract declares unknown method {name!r} — "
                     "init/locked entries must name real methods so "
                     "the exemption cannot outlive a rename")

    def visit(node: ast.AST, locked: bool) -> None:
        if isinstance(node, ast.With) and _is_lock_with(node, lock):
            for item in node.items:
                visit(item.context_expr, locked)
            for child in node.body:
                visit(child, True)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a closure runs later, when the lock is no longer held
            locked = False
        if not locked:
            for recv, attr, kind in _mutation_receivers(node):
                if attr in guards and isinstance(recv, ast.Name) \
                        and recv.id == "self":
                    scan.add(
                        "GL-C1", node, f"{cls.name}.{attr}",
                        f"write to guarded attribute {attr!r} outside "
                        f"'with self.{lock}:' — the contract declares "
                        f"{cls.name}.{lock} as its guard; take the "
                        "lock, or declare the method init/locked with "
                        "a docstring saying why that is safe")
        for child in ast.iter_child_nodes(node):
            visit(child, locked)

    for name, meth in methods.items():
        if name in exempt:
            continue
        for child in meth.body:
            visit(child, False)


def _check_c1_foreign(scan: _FileScan,
                      guarded_owners: Dict[str, List[Tuple[str, str]]]
                      ) -> None:
    """Cross-object reaches into guarded internals: ``a.b._guarded``.

    Bare-name receivers (``other._counters`` in ``registry.merge``)
    are deliberately exempt — a same-class parameter may be accessed
    under its own lock, which the AST cannot prove either way; the
    runtime twin covers that path. An *attribute* receiver is a
    different object's internals by construction."""
    for node in ast.walk(scan.tree):
        if not isinstance(node, ast.Attribute):
            continue
        owners = guarded_owners.get(node.attr)
        if not owners or not isinstance(node.value, ast.Attribute):
            continue
        owner_cls, lock = owners[0]
        recv = node.value.attr
        scan.add(
            "GL-C1", node, f"{recv}.{node.attr}",
            f"reach into {owner_cls}.{node.attr} (guarded by "
            f"{owner_cls}.{lock}) from outside the owning class; add "
            f"a locked accessor on {owner_cls} instead")


# --------------------------------------------------------------------------
# GL-C2: thread lifecycle
# --------------------------------------------------------------------------


def _is_thread_call(scan: _FileScan, call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread" \
            and isinstance(f.value, ast.Name):
        return scan.threading_names.get(f.value.id) == "threading"
    if isinstance(f, ast.Name):
        return scan.threading_names.get(f.id) == "Thread"
    return False


def _contains_join(nodes) -> bool:
    for n in nodes if isinstance(nodes, list) else [nodes]:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "join":
                return True
    return False


def _thread_target(call: ast.Call) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "target":
            return kw.value
    return call.args[0] if call.args else None


def _resolve_target(scan: _FileScan, target: Optional[ast.AST],
                    encl_class: Optional[ast.ClassDef]
                    ) -> Tuple[Optional[ast.FunctionDef],
                               Optional[ast.ClassDef]]:
    """(target function node, owning class) — (None, None) when the
    target is not statically resolvable (``httpd.serve_forever``)."""
    if target is None:
        return None, None
    name = _self_attr(target)
    if name is not None and encl_class is not None:
        meth = _class_methods(encl_class).get(name)
        return meth, encl_class if meth is not None else None
    if isinstance(target, ast.Name):
        for node in scan.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == target.id:
                return node, None
    return None, None


def _check_c2(scan: _FileScan,
              guarded_owners: Dict[str, List[Tuple[str, str]]]
              ) -> List[Tuple[ast.AST, Optional[ast.ClassDef]]]:
    """Check every Thread construction; return the resolved targets
    (for GL-C4)."""
    targets: List[Tuple[ast.AST, Optional[ast.ClassDef]]] = []

    def visit(node: ast.AST, stack: List[ast.AST]) -> None:
        if isinstance(node, ast.Call) and _is_thread_call(scan, node):
            encl_class = next((n for n in reversed(stack)
                               if isinstance(n, ast.ClassDef)), None)
            encl_func = next(
                (n for n in reversed(stack)
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))), None)
            daemon = next((kw.value for kw in node.keywords
                           if kw.arg == "daemon"), None)
            if not (isinstance(daemon, ast.Constant)
                    and daemon.value is True):
                scan.add(
                    "GL-C2", node, "Thread(daemon=...)",
                    "every thread in the package must be daemon=True "
                    "(a literal, so the linter can see it) — a "
                    "non-daemon sampler blocks interpreter shutdown")
            search = encl_class if encl_class is not None else scan.tree
            ok = _contains_join(search)
            if not ok and encl_func is not None:
                # returned to the caller, who owns the join
                # (the serve_http pattern: `return httpd, thread`)
                assigned = None
                for sub in ast.walk(encl_func):
                    if isinstance(sub, ast.Assign) and sub.value is node:
                        t = sub.targets[0]
                        if isinstance(t, ast.Name):
                            assigned = t.id
                for sub in ast.walk(encl_func):
                    if isinstance(sub, ast.Return) \
                            and sub.value is not None:
                        for leaf in ast.walk(sub.value):
                            if isinstance(leaf, ast.Name) \
                                    and leaf.id == assigned \
                                    and assigned is not None:
                                ok = True
                            if isinstance(leaf, ast.Call) \
                                    and leaf is node:
                                ok = True
            if not ok:
                scan.add(
                    "GL-C2", node, "Thread(no stop/join path)",
                    "thread started with no reachable join: register "
                    "it on the owner and join in a stop()/close()/"
                    "drain() method, or return it to the caller")
            tnode, towner = _resolve_target(scan, _thread_target(node),
                                            encl_class)
            if tnode is not None:
                targets.append((tnode, towner))
                own_guards = set()
                if towner is not None:
                    own_guards = set(
                        scan.contracts.get(towner.name, {})
                        .get("guards", ()))
                for sub in ast.walk(tnode):
                    for recv, attr, kind in _mutation_receivers(sub):
                        owners = guarded_owners.get(attr)
                        if not owners or attr in own_guards:
                            continue
                        if isinstance(recv, ast.Name) \
                                and recv.id != "self":
                            owner_cls, lock = owners[0]
                            scan.add(
                                "GL-C2", sub,
                                f"target mutates {recv.id}.{attr}",
                                "thread target mutates guarded state "
                                f"of a foreign class ({owner_cls}."
                                f"{attr}, guarded by {owner_cls}."
                                f"{lock}); route it through a locked "
                                "method on the owner")
        stack.append(node)
        for child in ast.iter_child_nodes(node):
            visit(child, stack)
        stack.pop()

    visit(scan.tree, [])
    return targets


# --------------------------------------------------------------------------
# GL-C3: atomic file outputs from threaded contexts
# --------------------------------------------------------------------------


def _write_mode(call: ast.Call) -> Optional[str]:
    """The literal mode of an ``open()``/``write_text`` style call
    that writes, else None."""
    f = call.func
    if isinstance(f, ast.Name) and f.id == "open":
        mode = None
        if len(call.args) >= 2 and isinstance(call.args[1],
                                              ast.Constant):
            mode = call.args[1].value
        for kw in call.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        if isinstance(mode, str) and any(c in mode for c in "wax"):
            return mode
        return None
    if isinstance(f, ast.Attribute) and f.attr in ("write_text",
                                                   "write_bytes"):
        return f.attr
    return None


def _contains_os_replace(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in ("replace", "rename") \
                and isinstance(sub.func.value, ast.Name) \
                and sub.func.value.id == "os":
            return True
    return False


def _check_c3_class(scan: _FileScan, cls: ast.ClassDef,
                    contract: dict) -> None:
    exempt = {"__init__"} | set(contract.get("init", ()))
    for name, meth in _class_methods(cls).items():
        if name in exempt:
            continue
        if _contains_os_replace(meth):
            continue
        for sub in ast.walk(meth):
            if isinstance(sub, ast.Call):
                mode = _write_mode(sub)
                if mode is not None:
                    scan.add(
                        "GL-C3", sub, f"{cls.name}.{name} open({mode!r})",
                        "file output from a threaded context without "
                        "the atomic idiom: write to '<path>.tmp' then "
                        "os.replace(tmp, path) so readers never see a "
                        "torn file (the FlightRecorder.dump "
                        "discipline)")


# --------------------------------------------------------------------------
# GL-C4: no silent swallowing in thread targets
# --------------------------------------------------------------------------


def _check_c4(scan: _FileScan,
              targets: List[Tuple[ast.AST, Optional[ast.ClassDef]]]
              ) -> None:
    seen = set()
    for tnode, towner in targets:
        if id(tnode) in seen:
            continue
        seen.add(id(tnode))
        owner = f"{towner.name}." if towner is not None else ""
        for sub in ast.walk(tnode):
            if not isinstance(sub, ast.ExceptHandler):
                continue
            if all(isinstance(s, (ast.Pass, ast.Continue))
                   for s in sub.body):
                scan.add(
                    "GL-C4", sub, f"{owner}{tnode.name} except:pass",
                    "bare swallow in a thread run loop hides real "
                    "failures as a silently stalled sampler; count a "
                    "telemetry counter (the MeshPlane.measure_ready "
                    "discipline: tel.counter('<plane>.sample_errors', "
                    "error=type(e).__name__)) before continuing")


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------


def _walk_files(root: str) -> List[str]:
    files = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__",))
        files += [os.path.join(dirpath, f) for f in sorted(filenames)
                  if f.endswith(".py")]
    return files


def contract_index(root: Optional[str] = None) -> Dict[str, dict]:
    """Every declared contract across the in-scope modules, keyed by
    class name: ``{"module": ..., "lock": ..., "guards": [...]}``.

    This is the report's ``concurrency.contracts`` block — committing
    it makes a contract added, widened, or dropped show up as a
    reviewable diff in ``analysis_report.json``."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    display_base = os.path.dirname(root)
    index: Dict[str, dict] = {}
    for f in _walk_files(root):
        display = os.path.relpath(f, display_base).replace(os.sep, "/")
        scope = os.path.relpath(f, root).replace(os.sep, "/")
        try:
            scan = _FileScan(f, display, tuple(scope.split("/")))
        except SyntaxError:
            continue
        if not scan.in_scope():
            continue
        for cls_name, spec in scan.contracts.items():
            index[cls_name] = {
                "module": display,
                "lock": spec["lock"],
                "guards": sorted(spec.get("guards", ())),
                "init": sorted(spec.get("init", ())),
                "locked": sorted(spec.get("locked", ())),
            }
    return dict(sorted(index.items()))


def run_concurrency_tier(root: Optional[str] = None,
                         display_base: Optional[str] = None
                         ) -> Tuple[List[Violation], int]:
    """Scan every ``.py`` under ``root`` (default: this package).

    Two passes: collect every module's ``GLC_CONTRACT`` first (the
    foreign-access arms need the package-wide guarded-attribute map),
    then apply GL-C1..C4 to the in-scope modules. Returns
    (violations, files_scanned) like ``run_ast_tier``.
    """
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if display_base is None:
        display_base = os.path.dirname(root)
    scans: List[_FileScan] = []
    for f in _walk_files(root):
        display = os.path.relpath(f, display_base).replace(os.sep, "/")
        scope = os.path.relpath(f, root).replace(os.sep, "/")
        scans.append(_FileScan(f, display, tuple(scope.split("/"))))

    guarded_owners: Dict[str, List[Tuple[str, str]]] = {}
    for scan in scans:
        if not scan.in_scope():
            continue
        for cls_name, spec in sorted(scan.contracts.items()):
            for attr in spec.get("guards", ()):
                guarded_owners.setdefault(attr, []).append(
                    (cls_name, spec["lock"]))

    out: List[Violation] = []
    for scan in scans:
        if not scan.in_scope():
            continue
        class_defs = {node.name: node for node in scan.tree.body
                      if isinstance(node, ast.ClassDef)}
        for cls_name, spec in sorted(scan.contracts.items()):
            cls = class_defs.get(cls_name)
            if cls is None:
                scan.add("GL-C1", scan.tree, cls_name,
                         f"contract declares unknown class {cls_name!r}"
                         " — the declaration must live next to the "
                         "class it covers")
                continue
            _check_c1_class(scan, cls, spec)
            _check_c3_class(scan, cls, spec)
        _check_c1_foreign(scan, guarded_owners)
        targets = _check_c2(scan, guarded_owners)
        _check_c4(scan, targets)
        out += scan.violations
    return out, len(scans)
