"""Violation records and the committed acceptance baseline.

A baseline entry deliberately matches on ``(code, path, symbol, kernel)``
and NOT on line numbers — accepted violations must survive unrelated
edits to the same file, and a *new* occurrence of the same symbol in the
same file is the same accepted fact, not a regression. Every entry
carries a mandatory human-written ``justification``; loading a baseline
with an empty one fails, so "baseline it" can never silently become
"ignore it".
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

#: the committed repo baseline, shipped inside the package
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")


@dataclasses.dataclass
class Violation:
    """One rule firing. ``path``/``line`` locate Tier-A findings in
    source; Tier-B findings locate by ``kernel`` instead (path='')."""

    code: str          # rule id, e.g. "GL-A1" / "GL-B1"
    path: str          # repo-relative posix path ('' for jaxpr tier)
    line: int          # 1-based source line (0 for jaxpr tier)
    symbol: str        # the offending symbol / primitive / call
    message: str       # human-readable explanation
    kernel: str = ""   # registered kernel name (jaxpr tier)

    def key(self) -> Tuple[str, str, str, str]:
        return (self.code, self.path, self.symbol, self.kernel)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def location(self) -> str:
        if self.kernel:
            return f"kernel:{self.kernel}"
        return f"{self.path}:{self.line}"


class Baseline:
    """The committed set of accepted violations."""

    def __init__(self, entries: Optional[List[dict]] = None):
        self.entries = entries or []
        for e in self.entries:
            if not str(e.get("justification", "")).strip():
                raise ValueError(
                    "baseline entry without a written justification: "
                    f"{e!r} — every accepted violation must say why")
        self._keys = {self._entry_key(e) for e in self.entries}

    @staticmethod
    def _entry_key(e: dict) -> Tuple[str, str, str, str]:
        return (e.get("code", ""), e.get("path", ""),
                e.get("symbol", ""), e.get("kernel", ""))

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls([])
        with open(path) as fh:
            text = fh.read()
        if not text.strip():  # /dev/null or a just-touched file
            return cls([])
        data = json.loads(text)
        if data.get("version") != 1:
            raise ValueError(f"unknown baseline version in {path}: "
                             f"{data.get('version')!r}")
        return cls(data.get("entries", []))

    def save(self, path: str) -> None:
        data = {"version": 1,
                "entries": sorted(self.entries,
                                  key=lambda e: self._entry_key(e))}
        with open(path, "w") as fh:
            json.dump(data, fh, indent=1)
            fh.write("\n")

    def split(self, violations: Iterable[Violation]
              ) -> Tuple[List[Violation], List[Violation], List[dict]]:
        """Partition into (new, accepted) and report stale entries.

        A stale entry matched nothing this run — usually the violation
        was fixed and the entry should be deleted; reported, not fatal.
        """
        new: List[Violation] = []
        accepted: List[Violation] = []
        hit: Dict[Tuple[str, str, str, str], bool] = {
            k: False for k in self._keys}
        for v in violations:
            if v.key() in self._keys:
                hit[v.key()] = True
                accepted.append(v)
            else:
                new.append(v)
        stale = [e for e in self.entries if not hit[self._entry_key(e)]]
        return new, accepted, stale

    def extend(self, violations: Iterable[Violation],
               justification: str) -> int:
        """Accept ``violations`` (deduped) under one justification."""
        if not justification.strip():
            raise ValueError("a justification is required to baseline "
                             "violations")
        added = 0
        for v in violations:
            if v.key() not in self._keys:
                self.entries.append({
                    "code": v.code, "path": v.path, "symbol": v.symbol,
                    "kernel": v.kernel, "justification": justification})
                self._keys.add(v.key())
                added += 1
        return added
