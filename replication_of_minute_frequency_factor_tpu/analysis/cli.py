"""graftlint CLI: ``python -m replication_of_minute_frequency_factor_tpu
analyze``.

Prints a one-line JSON verdict (the same convention as
``telemetry/regress.py``) and exits 0 iff the tree is clean against
the committed baseline. Default run: Tier A over the package + Tier B
over every registered kernel, report written to
``analysis_report.json`` at the repo root (diffable, committed).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from .violations import BASELINE_PATH, Baseline
from .report import build_report, repo_root, write_report


def add_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--tier", choices=("ast", "jaxpr", "all"),
                   default="all",
                   help="which tier(s) to run (default: all; the jaxpr "
                        "tier abstractly traces every registered "
                        "kernel — run it under JAX_PLATFORMS=cpu "
                        "locally, no accelerator needed)")
    p.add_argument("--baseline", default=BASELINE_PATH,
                   help="accepted-violations file (default: the "
                        "committed package baseline)")
    p.add_argument("--update-baseline", action="store_true",
                   help="accept every NEW violation into --baseline; "
                        "requires --justification")
    p.add_argument("--justification", default="",
                   help="written reason recorded on entries added by "
                        "--update-baseline (mandatory with it)")
    p.add_argument("--report", default=None, metavar="PATH",
                   help="where to write the machine-readable report "
                        "(default: <repo>/analysis_report.json; '-' "
                        "skips writing)")
    p.add_argument("--paths", nargs="*", default=None, metavar="DIR",
                   help="AST-tier scan roots (default: the installed "
                        "package); used by the fixture tests")
    p.add_argument("--days", type=int, default=2,
                   help="days extent of the canonical trace shape")
    p.add_argument("--tickers", type=int, default=3,
                   help="tickers extent of the canonical trace shape")
    p.add_argument("--rolling-impl", default="conv",
                   choices=("conv", "pallas", "pallas_interpret"),
                   help="rolling backend traced by the jaxpr tier")


def run(args: argparse.Namespace) -> int:
    from .ast_tier import run_ast_tier
    from .jaxpr_tier import SLOTS, run_jaxpr_tier

    violations = []
    n_files = 0
    if args.tier in ("ast", "all"):
        roots = args.paths if args.paths else [None]
        for root in roots:
            vs, nf = run_ast_tier(root)
            violations += vs
            n_files += nf
    fingerprints = None
    resident_fps = None
    session_fps = None
    shape = None
    if args.tier in ("jaxpr", "all"):
        from .jaxpr_tier import run_resident_tier, run_session_tier

        shape = (args.days, args.tickers, SLOTS)
        vs, fingerprints = run_jaxpr_tier(
            days=args.days, tickers=args.tickers,
            rolling_impl=args.rolling_impl)
        violations += vs
        # the resident scan wrappers (pipeline's year-in-one-executable
        # loops, single-device + tickers-sharded) trace at the same
        # canonical per-shard shape; their ONE driving scan is exempt
        # from GL-B1 by symbol (jaxpr_tier.RESIDENT_WRAPPERS), never
        # by baseline entry
        vs, resident_fps = run_resident_tier(
            days=args.days, tickers=args.tickers,
            rolling_impl=args.rolling_impl)
        violations += vs
        # per-session wrapper traces (ISSUE 15): every registered
        # market session's canonical shape fingerprints under the same
        # one-scan/zero-f64/zero-callback contract
        vs, session_fps = run_session_tier(
            days=args.days, tickers=args.tickers,
            rolling_impl=args.rolling_impl)
        violations += vs

    baseline = Baseline.load(args.baseline)
    new, accepted, stale = baseline.split(violations)

    if args.update_baseline and new:
        if not args.justification.strip():
            print("--update-baseline requires --justification "
                  "(every accepted violation must say why)",
                  file=sys.stderr)
            return 2
        baseline.extend(new, args.justification)
        baseline.save(args.baseline)
        new, accepted, stale = Baseline.load(args.baseline).split(
            violations)

    report = build_report(new, accepted, stale,
                          fingerprints=fingerprints,
                          files_scanned=n_files, shape=shape,
                          resident_fingerprints=resident_fps,
                          session_fingerprints=session_fps)
    report_path = args.report
    if report_path is None:
        import os
        report_path = os.path.join(repo_root(), "analysis_report.json")
    if report_path != "-":
        write_report(report_path, report)

    for v in new:
        print(f"{v.location()}: {v.code} [{v.symbol}] {v.message}",
              file=sys.stderr)
    for e in stale:
        print(f"stale baseline entry (violation no longer occurs — "
              f"delete it): {e}", file=sys.stderr)
    verdict = {"ok": not new, "tier": args.tier, **report["verdict"]}
    if fingerprints is not None:
        verdict["kernels"] = len(fingerprints)
    if resident_fps is not None:
        verdict["resident_wrappers"] = len(resident_fps)
    if session_fps is not None:
        verdict["sessions"] = len(session_fps)
    if report_path != "-":
        verdict["report"] = report_path
    print(json.dumps(verdict))
    return 0 if not new else 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m replication_of_minute_frequency_factor_tpu "
             "analyze",
        description=__doc__)
    add_args(ap)
    return run(ap.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
