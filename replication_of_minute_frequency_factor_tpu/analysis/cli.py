"""graftlint CLI: ``python -m replication_of_minute_frequency_factor_tpu
analyze``.

Prints a one-line JSON verdict (the same convention as
``telemetry/regress.py``) and exits 0 iff the tree is clean against
the committed baseline. Default run: Tier A over the package + Tier B
over every registered kernel, report written to
``analysis_report.json`` at the repo root (diffable, committed).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from .violations import BASELINE_PATH, Baseline
from .report import build_report, repo_root, write_report

#: ``--explain <CODE>``: rationale + fix pattern per rule, printable
#: without importing jax or tracing anything. Every code across all
#: three tiers appears here (docs/static-analysis.md is the long form).
EXPLAIN = {
    "GL-A1": ("jax attribute chain that does not exist on the pinned "
              "jax (the jnp.maximum.accumulate incident): the call "
              "fails only at runtime, on the accelerator host.",
              "Use an attribute that exists on the pinned jax, or gate "
              "behind hasattr with a tested fallback."),
    "GL-A2": ("serial Python/lax loop constructs in the kernel layers "
              "trace one program per iteration (the PR 3 rolling "
              "pathology) — compile times and HBM explode.",
              "Vectorise: windowed ops via ops.rolling / conv, batch "
              "via vmap; the one driving scan lives only in the "
              "resident wrappers."),
    "GL-A3": ("host-sync calls (block_until_ready, device_get, float()"
              " on a tracer) in device-hot modules serialize the "
              "dispatch pipeline.",
              "Keep results on device; sync only at the declared "
              "boundary modules listed in GLA3_BOUNDARY_SYNCS."),
    "GL-A4": ("resource acquisition (start_trace-style) without a "
              "guaranteed release leaks the resource on any exception "
              "path (the PR 2 bug).",
              "Pair acquire/release in try/finally or a context "
              "manager."),
    "GL-A5": ("raw jnp.mean/std/var/nan* in models/ silently disagree "
              "with the NaN-mask discipline the kernels mandate.",
              "Use the ops.masked reductions — same math, explicit "
              "mask semantics."),
    "GL-B0": ("a registered kernel failed to abstract-trace at the "
              "canonical shape — it cannot run at all.",
              "Fix the trace error; the jaxpr tier's error message "
              "carries the originating exception."),
    "GL-B1": ("while/scan primitives in a kernel jaxpr mean a serial "
              "loop survived into the compiled graph.",
              "Vectorise the computation; only the resident wrappers' "
              "ONE driving scan is exempt (by symbol, never by "
              "baseline)."),
    "GL-B2": ("an f64 convert_element_type in a kernel graph doubles "
              "memory and silently de-aligns from the f32 contract "
              "(the f64 oracle lives in tests only).",
              "Keep kernel dtypes f32/int32; cast explicitly in the "
              "test oracle, not the kernel."),
    "GL-B3": ("host callbacks (pure_callback/io_callback/debug."
              "callback) in a kernel graph stall the device on the "
              "host every step.",
              "Move host work outside the jitted graph."),
    "GL-A6": ("a @register kernel in models/ with no finalize-class "
              "declaration cannot state its exactness class, so the "
              "fast-finalize path must guess.",
              "Declare finalize_class(...) next to the kernel with "
              "one of the three exactness classes."),
    "GL-C1": ("a write/RMW of a declared guarded attribute outside "
              "'with self.<lock>:', or a cross-object reach into "
              "another class's guarded internals — exactly the race "
              "that works under CPython coincidence until it "
              "corrupts a scrape mid-flight.",
              "Take the owning lock around the mutation; for "
              "cross-object reads add a locked accessor on the owner "
              "(FleetRouter.inflight() is the pattern). Methods that "
              "genuinely run pre-thread go in the contract's 'init' "
              "tuple; caller-holds-lock helpers go in 'locked' — both "
              "with a docstring saying why."),
    "GL-C2": ("a thread that is not daemon=True blocks interpreter "
              "shutdown; one with no join path leaks; a target that "
              "mutates a foreign class's guarded state races its "
              "owner's lock.",
              "Construct threads daemon=True (literal), register them "
              "on the owner and join in stop()/close()/drain(), or "
              "return the thread to the caller who owns its "
              "lifecycle; route foreign-state writes through a locked "
              "method on the owner."),
    "GL-C3": ("a plain open('w') from a threaded context lets a "
              "scraper/reader see a torn half-written file.",
              "Write '<path>.tmp' then os.replace(tmp, path) — "
              "atomic on POSIX; FlightRecorder.dump is the exemplar."),
    "GL-C4": ("a bare except:pass in a thread run loop turns a real "
              "bug into a silently stalled sampler — nothing in any "
              "scrape says it died.",
              "Count a telemetry counter in the handler "
              "(tel.counter('<plane>.sample_errors', "
              "error=type(e).__name__)) so the failure is "
              "observable, then continue."),
}


def explain(code: str) -> int:
    spec = EXPLAIN.get(code.strip().upper())
    if spec is None:
        print(f"unknown rule code {code!r}; known: "
              + ", ".join(sorted(EXPLAIN)), file=sys.stderr)
        return 2
    why, fix = spec
    print(f"{code.strip().upper()}")
    print(f"  why: {why}")
    print(f"  fix: {fix}")
    return 0


def add_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--tier", choices=("ast", "jaxpr", "c", "all"),
                   default="all",
                   help="which tier(s) to run (default: all; the jaxpr "
                        "tier abstractly traces every registered "
                        "kernel — run it under JAX_PLATFORMS=cpu "
                        "locally, no accelerator needed; tier c is the "
                        "concurrency lint over the threaded layers)")
    p.add_argument("--explain", default=None, metavar="CODE",
                   help="print the rationale and fix pattern for one "
                        "rule code (e.g. GL-C1) and exit")
    p.add_argument("--baseline", default=BASELINE_PATH,
                   help="accepted-violations file (default: the "
                        "committed package baseline)")
    p.add_argument("--update-baseline", action="store_true",
                   help="accept every NEW violation into --baseline; "
                        "requires --justification")
    p.add_argument("--justification", default="",
                   help="written reason recorded on entries added by "
                        "--update-baseline (mandatory with it)")
    p.add_argument("--report", default=None, metavar="PATH",
                   help="where to write the machine-readable report "
                        "(default: <repo>/analysis_report.json; '-' "
                        "skips writing)")
    p.add_argument("--paths", nargs="*", default=None, metavar="DIR",
                   help="AST-tier scan roots (default: the installed "
                        "package); used by the fixture tests")
    p.add_argument("--days", type=int, default=2,
                   help="days extent of the canonical trace shape")
    p.add_argument("--tickers", type=int, default=3,
                   help="tickers extent of the canonical trace shape")
    p.add_argument("--rolling-impl", default="conv",
                   choices=("conv", "pallas", "pallas_interpret"),
                   help="rolling backend traced by the jaxpr tier")


def run(args: argparse.Namespace) -> int:
    if getattr(args, "explain", None):
        return explain(args.explain)

    from .ast_tier import run_ast_tier
    from .jaxpr_tier import SLOTS, run_jaxpr_tier

    violations = []
    n_files = 0
    if args.tier in ("ast", "all"):
        roots = args.paths if args.paths else [None]
        for root in roots:
            vs, nf = run_ast_tier(root)
            violations += vs
            n_files += nf
    concurrency = None
    if args.tier in ("c", "all"):
        from .concurrency_tier import contract_index, run_concurrency_tier

        c_violations = []
        c_files = 0
        contracts = {}
        roots = args.paths if args.paths else [None]
        for root in roots:
            vs, nf = run_concurrency_tier(root)
            c_violations += vs
            c_files += nf
            contracts.update(contract_index(root))
        violations += c_violations
        concurrency = {
            "files_scanned": c_files,
            "contracts": contracts,
            "by_rule": {},
        }
        for v in c_violations:
            concurrency["by_rule"][v.code] = \
                concurrency["by_rule"].get(v.code, 0) + 1
        concurrency["by_rule"] = dict(
            sorted(concurrency["by_rule"].items()))
    fingerprints = None
    resident_fps = None
    session_fps = None
    shape = None
    if args.tier in ("jaxpr", "all"):
        from .jaxpr_tier import run_resident_tier, run_session_tier

        shape = (args.days, args.tickers, SLOTS)
        vs, fingerprints = run_jaxpr_tier(
            days=args.days, tickers=args.tickers,
            rolling_impl=args.rolling_impl)
        violations += vs
        # the resident scan wrappers (pipeline's year-in-one-executable
        # loops, single-device + tickers-sharded) trace at the same
        # canonical per-shard shape; their ONE driving scan is exempt
        # from GL-B1 by symbol (jaxpr_tier.RESIDENT_WRAPPERS), never
        # by baseline entry
        vs, resident_fps = run_resident_tier(
            days=args.days, tickers=args.tickers,
            rolling_impl=args.rolling_impl)
        violations += vs
        # per-session wrapper traces (ISSUE 15): every registered
        # market session's canonical shape fingerprints under the same
        # one-scan/zero-f64/zero-callback contract
        vs, session_fps = run_session_tier(
            days=args.days, tickers=args.tickers,
            rolling_impl=args.rolling_impl)
        violations += vs

    baseline = Baseline.load(args.baseline)
    new, accepted, stale = baseline.split(violations)

    if args.update_baseline and new:
        if not args.justification.strip():
            print("--update-baseline requires --justification "
                  "(every accepted violation must say why)",
                  file=sys.stderr)
            return 2
        baseline.extend(new, args.justification)
        baseline.save(args.baseline)
        new, accepted, stale = Baseline.load(args.baseline).split(
            violations)

    report = build_report(new, accepted, stale,
                          fingerprints=fingerprints,
                          files_scanned=n_files, shape=shape,
                          resident_fingerprints=resident_fps,
                          session_fingerprints=session_fps,
                          concurrency=concurrency)
    report_path = args.report
    if report_path is None:
        import os
        report_path = os.path.join(repo_root(), "analysis_report.json")
    if report_path != "-":
        write_report(report_path, report)

    for v in new:
        print(f"{v.location()}: {v.code} [{v.symbol}] {v.message}",
              file=sys.stderr)
    for e in stale:
        print(f"stale baseline entry (violation no longer occurs — "
              f"delete it): {e}", file=sys.stderr)
    verdict = {"ok": not new, "tier": args.tier, **report["verdict"]}
    if fingerprints is not None:
        verdict["kernels"] = len(fingerprints)
    if resident_fps is not None:
        verdict["resident_wrappers"] = len(resident_fps)
    if session_fps is not None:
        verdict["sessions"] = len(session_fps)
    if concurrency is not None:
        verdict["contracts"] = len(concurrency["contracts"])
    if report_path != "-":
        verdict["report"] = report_path
    print(json.dumps(verdict))
    return 0 if not new else 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m replication_of_minute_frequency_factor_tpu "
             "analyze",
        description=__doc__)
    add_args(ap)
    return run(ap.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
