"""Tier B: jaxpr contract checker over the registered kernels.

Every kernel in ``models/registry.py`` is abstractly traced (no data,
no compile — ``jax.make_jaxpr`` on ``ShapeDtypeStruct`` inputs) at the
canonical ``(days, tickers, 240)`` layout, and the closed jaxpr is
walked recursively (through cond branches, custom_jvp call jaxprs,
pjit bodies, ...) to enforce per-kernel contracts:

GL-B1  zero ``while``/``scan`` primitives — a ``fori_loop`` traces to
       ``scan`` (static trip count) or ``while``, and both lower to a
       serial XLA ``while`` op: the exact pathology the PR 3 fused
       rolling engine removed. This gate makes that win permanent.
GL-B2  zero f64 ``convert_element_type`` — the f64 oracle lives in
       ``oracle/`` only; an f64 promotion inside a kernel silently
       doubles HBM traffic and diverges from the f32 policy.
GL-B3  zero host callbacks (``pure_callback``/``io_callback``/
       ``debug_callback``) — a kernel that calls back into Python
       cannot be fused, donated, or sharded.

Alongside the verdict, each kernel reports a primitive-count
fingerprint ``{primitive: count}``; committed into
``analysis_report.json``, a graph-shape drift (an op class appearing
or a count jumping) shows up as a reviewable diff.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .violations import Violation

#: canonical trailing layout: (days, tickers, SLOTS) with 5 bar fields
SLOTS = 240
N_FIELDS = 5

#: serial loop primitives (both lower to an XLA ``while``)
BANNED_LOOP_PRIMS = ("while", "scan")

#: wide dtypes banned outside oracle/ (names as str(dtype))
BANNED_WIDE_DTYPES = ("float64", "complex128")


def _iter_jaxprs(obj):
    """Yield every Jaxpr reachable from ``obj`` (params may hold
    ClosedJaxpr, Jaxpr, or tuples/lists of either — e.g. cond's
    ``branches``)."""
    from jax._src import core  # stable across 0.4.x for these names

    if isinstance(obj, core.ClosedJaxpr):
        yield obj.jaxpr
    elif isinstance(obj, core.Jaxpr):
        yield obj
    elif isinstance(obj, (tuple, list)):
        for x in obj:
            yield from _iter_jaxprs(x)


def _walk_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _iter_jaxprs(v):
                yield from _walk_eqns(sub)


def primitive_counts(closed) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for eqn in _walk_eqns(closed.jaxpr):
        counts[eqn.primitive.name] = counts.get(eqn.primitive.name,
                                                0) + 1
    return counts


def kernel_jaxpr(fn: Callable, days: int = 2, tickers: int = 3,
                 rolling_impl: str = "conv"):
    """Abstractly trace ``fn(ctx)`` at the canonical shape."""
    import jax
    import jax.numpy as jnp

    from ..models.context import DayContext

    bars = jax.ShapeDtypeStruct((days, tickers, SLOTS, N_FIELDS),
                                jnp.float32)
    mask = jax.ShapeDtypeStruct((days, tickers, SLOTS), jnp.bool_)

    def wrapped(b, m):
        return fn(DayContext(b, m, rolling_impl=rolling_impl))

    return jax.make_jaxpr(wrapped)(bars, mask)


def check_kernel(name: str, fn: Callable, days: int = 2,
                 tickers: int = 3, rolling_impl: str = "conv"
                 ) -> Tuple[List[Violation], Dict]:
    """Contracts + fingerprint for one kernel. A kernel that fails to
    trace at all is itself a violation (GL-B0) — every registered
    kernel must be jit-traceable at the canonical shape."""
    try:
        closed = kernel_jaxpr(fn, days, tickers, rolling_impl)
    except Exception as e:  # noqa: BLE001 — the failure IS the finding
        v = Violation(code="GL-B0", path="", line=0,
                      symbol=f"{type(e).__name__}",
                      message=f"kernel failed to trace at "
                              f"({days}, {tickers}, {SLOTS}): {e}",
                      kernel=name)
        return [v], {"traced": False}
    out: List[Violation] = []
    counts = primitive_counts(closed)
    for prim in BANNED_LOOP_PRIMS:
        if counts.get(prim):
            out.append(Violation(
                code="GL-B1", path="", line=0, symbol=prim,
                message=f"{counts[prim]}x '{prim}' primitive in the "
                        "kernel jaxpr — lowers to a serial XLA while "
                        "(the pre-PR-3 rolling pathology); use the "
                        "unrolled/batched formulation", kernel=name))
    for eqn in _walk_eqns(closed.jaxpr):
        if eqn.primitive.name == "convert_element_type":
            dt = str(eqn.params.get("new_dtype", ""))
            if dt in BANNED_WIDE_DTYPES:
                out.append(Violation(
                    code="GL-B2", path="", line=0,
                    symbol=f"convert_element_type[{dt}]",
                    message="f64 promotion inside a kernel: wide "
                            "dtypes belong to oracle/ only (f32 "
                            "policy)", kernel=name))
        if "callback" in eqn.primitive.name:
            out.append(Violation(
                code="GL-B3", path="", line=0,
                symbol=eqn.primitive.name,
                message="host callback inside a kernel defeats "
                        "fusion/donation/sharding; kernels must be "
                        "pure device graphs", kernel=name))
    fingerprint = {"traced": True,
                   "n_eqns": sum(counts.values()),
                   "primitives": dict(sorted(counts.items()))}
    return out, fingerprint


def run_jaxpr_tier(names: Optional[Sequence[str]] = None, days: int = 2,
                   tickers: int = 3, rolling_impl: str = "conv"
                   ) -> Tuple[List[Violation], Dict[str, Dict]]:
    """Check every registered kernel (default: the canonical 58)."""
    from ..models import registry

    if names is None:
        names = registry.factor_names()
    violations: List[Violation] = []
    fingerprints: Dict[str, Dict] = {}
    for n in names:
        vs, fp = check_kernel(n, registry.resolve(n), days=days,
                              tickers=tickers, rolling_impl=rolling_impl)
        violations += vs
        fingerprints[n] = fp
    return violations, fingerprints


# --------------------------------------------------------------------------
# driving-scan wrappers (the pipeline's year-in-one-executable loops +
# the streaming minute fold)
# --------------------------------------------------------------------------

#: wrapper symbols exempted from GL-B1's zero-scan rule BY SYMBOL, not
#: by baseline entry: the driving ``scan`` — over the year's batches
#: (resident mode, the O(1)-round-trip point) or over a micro-batch's
#: minutes (``stream/engine.scan_update``, ISSUE 7) — IS the wrapper's
#: loop shape. Exactly ONE scan is allowed — a second one means a
#: serial loop leaked out of a kernel and through the wrapper, the
#: exact regression GL-B1 guards against — and ``while`` stays banned.
#: ``__result_encode__`` (ISSUE 10) is the result wire's on-device
#: encode (``data/result_wire.encode_block``): it fuses into every
#: producing graph as the final stage, so it gets NO scan exemption at
#: all — zero while, zero scan, zero f64, zero callbacks, the full
#: kernel contract (its cumsum/scatter compaction must never trace to
#: a serial loop).
#: ``__resident_scan_2d__`` (ISSUE 13) is the 2-D (days, tickers)
#: pipelined scan: ONE driving scan like its 1-D siblings, zero
#: while/f64/callbacks — and its fingerprint must carry ``ppermute``
#: (the cross-day carry handoff is counted in the collective class;
#: the leg is emitted even on the one-device trace mesh precisely so
#: the reserved symbol's committed fingerprint pins it).
#: ``__discover_generation__`` (ISSUE 14) is the factor-discovery
#: engine's per-generation fitness graph
#: (``research/fitness.generation_fitness_sharded``): evaluation +
#: IC + decile spread fused per population chunk, folded through ONE
#: sequential ``lax.map`` (the HBM-bounding driving scan — traced
#: with chunk < pop so the scan is always in the fingerprint), zero
#: while/f64/host-callbacks, and the fingerprint pins the
#: end-of-generation top-k gather's collective class (all_gather +
#: top_k — emitted on the one-device trace mesh like the 2-D scan's
#: ppermute).
#: ``__stream_finalize_fast__`` (ISSUE 18) is the O(1)-per-bar fast
#: finalize (``stream/fastpath.stream_finalize_fast``): the foldable
#: kernel subset materialized from the carry's sufficient statistics
#: alone. It is scan-free BY CONSTRUCTION — pure elementwise math over
#: [T]-shaped accumulator leaves, no bar-buffer read — so like
#: ``__result_encode__`` it gets NO scan exemption: zero while, zero
#: scan, zero f64, zero callbacks. A scan appearing in this fingerprint
#: means a sequential fold leaked into what must stay a closed-form
#: materialization (the cost_analysis O(1) claim would silently rot).
RESIDENT_WRAPPERS = ("__resident_scan__", "__resident_scan_sharded__",
                     "__resident_scan_2d__",
                     "__stream_update__", "__result_encode__",
                     "__stream_finalize_fast__",
                     "__discover_generation__")

#: allowed driving-scan count per wrapper symbol (default 1)
WRAPPER_SCAN_ALLOWANCE = {"__result_encode__": 0,
                          "__stream_finalize_fast__": 0}

#: factor subset the wrapper traces drive: re-tracing all 58 kernels a
#: third time per analyze run buys no new contract coverage (the kernel
#: tier owns them); these cover the shape classes — a plain reduction,
#: the rolling scan-free family, and the one cross-sectional collective
RESIDENT_TRACE_NAMES = ("vol_return1min", "mmt_ols_qrs", "doc_pdf60")


def resident_wrapper_jaxprs(n_batches: int = 2, days: int = 2,
                            tickers: int = 3,
                            rolling_impl: str = "conv") -> Dict[str, object]:
    """Abstractly trace the resident scan entrypoints at the canonical
    per-shard shape: the single-device ``_compute_packed_scan`` on a
    tuple of packed-buffer ShapeDtypeStructs, and the sharded
    ``_compute_packed_scan_sharded`` through its ``shard_map`` on a
    one-device tickers mesh (the per-shard module is what every shard
    runs, so one shard IS the canonical trace). The raw packed kind
    keeps the trace free of wire-format coupling; the spec comes from
    a real (zero-filled) ``pack_arrays`` call so it can never drift
    from the packer.

    ``__stream_update__`` (ISSUE 7) is the streaming engine's
    minutes-fold ``scan_update`` traced over the canonical carry at
    ``n_batches`` minutes: its driving scan advances the carry one bar
    column per step, and the SAME one-scan/zero-f64/zero-callback
    contract applies."""
    import jax
    import numpy as np

    from .. import pipeline
    from ..data import wire
    from ..parallel.mesh import make_mesh
    from ..stream import carry as stream_carry
    from ..stream.engine import scan_update

    bars = np.zeros((days, tickers, SLOTS, N_FIELDS), np.float32)
    mask = np.zeros((days, tickers, SLOTS), np.uint8)
    buf, spec = wire.pack_arrays((bars, mask))
    names = RESIDENT_TRACE_NAMES
    bufs = tuple(jax.ShapeDtypeStruct(buf.shape, buf.dtype)
                 for _ in range(n_batches))
    out = {"__resident_scan__": jax.make_jaxpr(
        lambda b: pipeline._compute_packed_scan(
            b, spec, "raw", names, True, rolling_impl))(bufs)}
    mesh = make_mesh((1, 1), jax.devices()[:1])
    stacked = jax.ShapeDtypeStruct((n_batches, 1, buf.shape[0]),
                                   np.uint8)
    out["__resident_scan_sharded__"] = jax.make_jaxpr(
        lambda s: pipeline._compute_packed_scan_sharded(
            s, spec, "raw", names, True, rolling_impl, mesh))(stacked)
    # the 2-D pipelined scan (ISSUE 13) at the canonical per-tile
    # shape on the same one-device mesh: the per-tile module is what
    # every (day-shard, ticker-shard) runs, and the carry-handoff leg
    # emits its ppermute even at day-axis extent 1 so the fingerprint
    # carries the collective class
    stacked_2d = jax.ShapeDtypeStruct((n_batches, 1, 1, buf.shape[0]),
                                      np.uint8)
    carry_sds_2d = {
        "last_close": jax.ShapeDtypeStruct((tickers,), np.float32),
        "n_bars": jax.ShapeDtypeStruct((tickers,), np.int32),
        "has": jax.ShapeDtypeStruct((tickers,), np.bool_),
    }
    out["__resident_scan_2d__"] = jax.make_jaxpr(
        lambda s, c: pipeline._compute_packed_scan_2d(
            s, c, spec, "raw", names, True, rolling_impl,
            mesh))(stacked_2d, carry_sds_2d)
    carry_sds = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                       np.asarray(x).dtype),
        stream_carry.init_carry(tickers))
    out["__stream_update__"] = jax.make_jaxpr(scan_update)(
        carry_sds,
        jax.ShapeDtypeStruct((n_batches, tickers, N_FIELDS),
                             np.float32),
        jax.ShapeDtypeStruct((n_batches, tickers), np.bool_))
    # the fast finalize (ISSUE 18), traced over the carry's statistic
    # leaves at the full foldable factor set — the committed
    # fingerprint pins the scan-free closed-form materialization
    from ..models.registry import factor_names
    from ..stream import fastpath

    fold_names, _ = fastpath.partition_names(factor_names())
    out["__stream_finalize_fast__"] = jax.make_jaxpr(
        lambda i: fastpath.stream_finalize_fast(i, fold_names))(
        carry_sds["inc"])
    # the result-wire encode (ISSUE 10), traced standalone at the
    # canonical [F, days, tickers] block shape with the default spec —
    # the SAME graph every producing path fuses as its final stage
    from ..data import result_wire

    rspec = result_wire.ResultWireSpec.for_names(names, days=days)
    out["__result_encode__"] = jax.make_jaxpr(
        lambda x: result_wire.encode_block(x, rspec))(
            jax.ShapeDtypeStruct((len(names), days, tickers),
                                 np.float32))
    # the discovery generation graph (ISSUE 14) on the same one-device
    # mesh at a canonical pop=4/chunk=2 shape: chunk < pop forces the
    # HBM-bounding lax.map into the trace (it IS the allowed driving
    # scan), and the top-k gather emits its all_gather even at mesh
    # extent 1 so the committed fingerprint pins the collective class
    from ..research import fitness as research_fitness
    from ..search import DEFAULT_SKELETON

    pop = 4
    out["__discover_generation__"] = jax.make_jaxpr(
        lambda g, b, m, r, v:
        research_fitness.generation_fitness_sharded(
            g, b, m, r, v, mesh=mesh, skeleton=DEFAULT_SKELETON,
            group_num=5, chunk=2, n_elite=2, n_pop=pop))(
        jax.ShapeDtypeStruct((pop, len(DEFAULT_SKELETON)), np.int32),
        jax.ShapeDtypeStruct((days, tickers, SLOTS, N_FIELDS),
                             np.float32),
        jax.ShapeDtypeStruct((days, tickers, SLOTS), np.bool_),
        jax.ShapeDtypeStruct((days, tickers), np.float32),
        jax.ShapeDtypeStruct((days, tickers), np.bool_))
    return out


def check_resident_wrapper(name: str, closed) -> Tuple[List[Violation],
                                                       Dict]:
    """Kernel contracts adapted to a resident wrapper: GL-B2/GL-B3
    unchanged; GL-B1 becomes "zero ``while``, exactly one ``scan``"
    (see :data:`RESIDENT_WRAPPERS`)."""
    out: List[Violation] = []
    counts = primitive_counts(closed)
    if counts.get("while"):
        out.append(Violation(
            code="GL-B1", path="", line=0, symbol="while",
            message=f"{counts['while']}x 'while' primitive in the "
                    "resident wrapper jaxpr — only the single driving "
                    "scan is exempt; a while is a serial loop leaking "
                    "through", kernel=name))
    n_scan = counts.get("scan", 0)
    # session-tier names arrive prefixed ("us_390:__stream_update__");
    # the allowance is keyed by the bare wrapper symbol
    allowed = WRAPPER_SCAN_ALLOWANCE.get(name.rsplit(":", 1)[-1], 1)
    if n_scan != allowed:
        out.append(Violation(
            code="GL-B1", path="", line=0, symbol="scan",
            message=f"{n_scan}x 'scan' primitives in the resident "
                    f"wrapper jaxpr (symbol allows {allowed}) — the "
                    "exemption covers exactly the driving scan(s); "
                    "anything more is a serial loop leaking through",
            kernel=name))
    for eqn in _walk_eqns(closed.jaxpr):
        if eqn.primitive.name == "convert_element_type":
            dt = str(eqn.params.get("new_dtype", ""))
            if dt in BANNED_WIDE_DTYPES:
                out.append(Violation(
                    code="GL-B2", path="", line=0,
                    symbol=f"convert_element_type[{dt}]",
                    message="f64 promotion inside the resident "
                            "wrapper: wide dtypes belong to oracle/ "
                            "only (f32 policy)", kernel=name))
        if "callback" in eqn.primitive.name:
            out.append(Violation(
                code="GL-B3", path="", line=0,
                symbol=eqn.primitive.name,
                message="host callback inside the resident wrapper "
                        "defeats fusion/donation/sharding",
                kernel=name))
    fingerprint = {"traced": True,
                   "n_eqns": sum(counts.values()),
                   "primitives": dict(sorted(counts.items()))}
    return out, fingerprint


#: wrapper symbols re-traced per REGISTERED SESSION (ISSUE 15): the
#: session-coupled contract surface is (a) the fused
#: unpack+decode+kernel scan and (b) the streaming minute fold — the
#: 2-D/discover/result wrappers layer sharding or [F, D, T] blocks on
#: top of (a) and add no further slot-count coupling, so re-tracing
#: them per session buys no new contract coverage.
#: ``__stream_finalize_fast__`` (ISSUE 18) is traced per session
#: precisely because it must NOT vary: its inputs are [T]-shaped
#: statistic leaves with no slot-count coupling, so equal per-session
#: fingerprints ARE the committed O(1)-in-session-length evidence (a
#: session-dependent fingerprint means the fast graph started reading
#: the bar buffer).
SESSION_TRACE_WRAPPERS = ("__resident_scan__", "__stream_update__",
                          "__stream_finalize_fast__")


def session_wrapper_jaxprs(session, n_batches: int = 2, days: int = 2,
                           tickers: int = 3,
                           rolling_impl: str = "conv") -> Dict[str, object]:
    """Abstractly trace :data:`SESSION_TRACE_WRAPPERS` at one
    registered session's canonical shape (``(days, tickers,
    session.n_slots)``): the resident scan over raw packed buffers of
    that day shape, and the streaming minute fold over that session's
    carry. Same contracts as the canonical wrappers (one driving
    scan, zero while/f64/callbacks)."""
    import jax
    import numpy as np

    from .. import pipeline
    from ..data import wire
    from ..markets import get_session
    from ..stream import carry as stream_carry
    from ..stream.engine import scan_update

    spec_s = get_session(session)
    n_slots = spec_s.n_slots
    bars = np.zeros((days, tickers, n_slots, N_FIELDS), np.float32)
    mask = np.zeros((days, tickers, n_slots), np.uint8)
    buf, spec = wire.pack_arrays((bars, mask))
    names = RESIDENT_TRACE_NAMES
    bufs = tuple(jax.ShapeDtypeStruct(buf.shape, buf.dtype)
                 for _ in range(n_batches))
    out = {"__resident_scan__": jax.make_jaxpr(
        lambda b: pipeline._compute_packed_scan(
            b, spec, "raw", names, True, rolling_impl, None, False,
            spec_s))(bufs)}
    carry_sds = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                       np.asarray(x).dtype),
        stream_carry.init_carry(tickers, session=spec_s))
    out["__stream_update__"] = jax.make_jaxpr(
        lambda c, b, p: scan_update(c, b, p, session=spec_s))(
        carry_sds,
        jax.ShapeDtypeStruct((n_batches, tickers, N_FIELDS),
                             np.float32),
        jax.ShapeDtypeStruct((n_batches, tickers), np.bool_))
    from ..models.registry import factor_names
    from ..stream import fastpath
    fold_names, _ = fastpath.partition_names(factor_names())
    out["__stream_finalize_fast__"] = jax.make_jaxpr(
        lambda i: fastpath.stream_finalize_fast(i, fold_names))(
        carry_sds["inc"])
    return out


def run_session_tier(n_batches: int = 2, days: int = 2, tickers: int = 3,
                     rolling_impl: str = "conv"
                     ) -> Tuple[List[Violation],
                                Dict[str, Dict[str, Dict]]]:
    """Per-session wrapper contracts + fingerprints (ISSUE 15): every
    REGISTERED session's canonical shape is traced and fingerprinted,
    so registering a market puts its graph shape under the same
    drift-diffable commit as the canonical 240 one. The canonical
    session is included — its rows must agree with the canonical
    wrapper fingerprints' session-coupled subset."""
    from ..markets import session_names

    violations: List[Violation] = []
    fingerprints: Dict[str, Dict[str, Dict]] = {}
    for sname in session_names():
        try:
            jaxprs = session_wrapper_jaxprs(
                sname, n_batches=n_batches, days=days, tickers=tickers,
                rolling_impl=rolling_impl)
        except Exception as e:  # noqa: BLE001 — the failure IS the finding
            for wname in SESSION_TRACE_WRAPPERS:
                violations.append(Violation(
                    code="GL-B0", path="", line=0,
                    symbol=f"{type(e).__name__}",
                    message=f"session {sname!r} wrapper failed to "
                            f"trace at ({days}, {tickers}, "
                            f"session.n_slots): {e}",
                    kernel=f"{sname}:{wname}"))
            fingerprints[sname] = {w: {"traced": False}
                                   for w in SESSION_TRACE_WRAPPERS}
            continue
        rows: Dict[str, Dict] = {}
        for wname, closed in jaxprs.items():
            vs, fp = check_resident_wrapper(f"{sname}:{wname}", closed)
            violations += vs
            rows[wname] = fp
        fingerprints[sname] = rows
    return violations, fingerprints


def run_resident_tier(n_batches: int = 2, days: int = 2,
                      tickers: int = 3, rolling_impl: str = "conv"
                      ) -> Tuple[List[Violation], Dict[str, Dict]]:
    """Contracts + fingerprints for the resident scan wrappers. A
    wrapper that fails to trace is a GL-B0 finding, same as a
    kernel."""
    violations: List[Violation] = []
    fingerprints: Dict[str, Dict] = {}
    try:
        jaxprs = resident_wrapper_jaxprs(n_batches=n_batches, days=days,
                                         tickers=tickers,
                                         rolling_impl=rolling_impl)
    except Exception as e:  # noqa: BLE001 — the failure IS the finding
        for name in RESIDENT_WRAPPERS:
            violations.append(Violation(
                code="GL-B0", path="", line=0,
                symbol=f"{type(e).__name__}",
                message=f"resident wrapper failed to trace at "
                        f"({days}, {tickers}, {SLOTS}): {e}",
                kernel=name))
            fingerprints[name] = {"traced": False}
        return violations, fingerprints
    for name, closed in jaxprs.items():
        vs, fp = check_resident_wrapper(name, closed)
        violations += vs
        fingerprints[name] = fp
    return violations, fingerprints
