"""Report assembly: the machine-readable graftlint verdict.

``analysis_report.json`` (committed at the repo root) is the durable
artifact: verdict, per-rule counts, and the per-kernel primitive
fingerprints whose diffs make graph drift reviewable. The condensed
``manifest_block`` rides in every telemetry run manifest so "was the
tree contract-clean when these numbers were produced" is answerable
from the bundle alone.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

from .violations import BASELINE_PATH, Baseline, Violation

SCHEMA = "graftlint/1"


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _rule_counts(violations: List[Violation]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for v in violations:
        out[v.code] = out.get(v.code, 0) + 1
    return dict(sorted(out.items()))


def build_report(new: List[Violation], accepted: List[Violation],
                 stale: List[dict],
                 fingerprints: Optional[Dict[str, Dict]] = None,
                 files_scanned: int = 0,
                 shape: Optional[tuple] = None,
                 resident_fingerprints: Optional[Dict[str, Dict]] = None,
                 session_fingerprints: Optional[Dict[str, Dict]] = None,
                 concurrency: Optional[dict] = None) -> dict:
    try:
        import jax
        jax_version = jax.__version__
    except Exception:  # noqa: BLE001 — report must build without jax
        jax_version = None
    report = {
        "schema": SCHEMA,
        "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
        "jax_version": jax_version,
        "verdict": {
            "clean": not new,
            "new": len(new),
            "baselined": len(accepted),
            "stale_baseline": len(stale),
            "by_rule": _rule_counts(new),
        },
        "files_scanned": files_scanned,
        "violations": [v.to_dict() for v in new],
        "baselined": [v.to_dict() for v in accepted],
        "stale_baseline_entries": stale,
    }
    if fingerprints is not None:
        report["jaxpr"] = {
            "shape": list(shape) if shape else None,
            "kernels": len(fingerprints),
            "fingerprints": {k: fingerprints[k]
                             for k in sorted(fingerprints)},
        }
        if resident_fingerprints:
            # kept apart from the kernel fingerprints: the wrappers are
            # not kernels, and their GL-B1 exemption (one driving scan)
            # must never blur the kernels' zero-scan contract
            report["jaxpr"]["resident_wrappers"] = {
                k: resident_fingerprints[k]
                for k in sorted(resident_fingerprints)}
        if session_fingerprints:
            # per-session wrapper fingerprints (ISSUE 15): one row per
            # REGISTERED market session, traced at that session's
            # canonical (days, tickers, n_slots) shape — registering a
            # new market lands its graph shape here, where a drift
            # shows up as a reviewable diff like the kernel rows above
            report["jaxpr"]["sessions"] = {
                k: session_fingerprints[k]
                for k in sorted(session_fingerprints)}
    if concurrency is not None:
        # Tier C summary (ISSUE 19): which classes declared contracts
        # and what the lock-discipline sweep found — committed so a
        # contract added/dropped in review shows up as a diff here
        report["concurrency"] = concurrency
    return report


def write_report(path: str, report: dict) -> str:
    with open(path, "w") as fh:
        json.dump(report, fh, indent=1, sort_keys=False)
        fh.write("\n")
    return path


_manifest_memo: Optional[dict] = None


def manifest_block(refresh: bool = False) -> dict:
    """Condensed verdict for the telemetry run manifest.

    Re-runs the (fast, parse-only) AST tier live against the committed
    baseline, and condenses the committed ``analysis_report.json`` for
    the jaxpr side — re-tracing 58 kernels per telemetry write would
    not be. Memoised per process: the tree does not change mid-run.
    """
    global _manifest_memo
    if _manifest_memo is not None and not refresh:
        return _manifest_memo
    from .ast_tier import run_ast_tier

    violations, n_files = run_ast_tier()
    baseline = Baseline.load(BASELINE_PATH)
    new, accepted, stale = baseline.split(violations)
    block = {
        "ast": {"clean": not new, "new": len(new),
                "baselined": len(accepted),
                "stale_baseline": len(stale),
                "files_scanned": n_files,
                "by_rule": _rule_counts(new)},
    }
    report_path = os.path.join(repo_root(), "analysis_report.json")
    if os.path.exists(report_path):
        try:
            with open(report_path) as fh:
                rep = json.load(fh)
            block["report"] = {
                "present": True,
                "created_utc": rep.get("created_utc"),
                "clean": rep.get("verdict", {}).get("clean"),
                "kernels": rep.get("jaxpr", {}).get("kernels"),
            }
        except (OSError, ValueError) as e:
            block["report"] = {"present": False,
                               "error": f"{type(e).__name__}: {e}"}
    else:
        block["report"] = {"present": False}
    _manifest_memo = block
    return block
