"""graftlint — static contract analysis for the 58-kernel factor engine.

Three tiers (docs/static-analysis.md):

* **Tier A** (:mod:`.ast_tier`) — a rule engine over the package's
  Python AST. Rules GL-A1..GL-A5 encode the bug classes earlier PRs
  found by archaeology: jax attributes that don't exist on the pinned
  jax (the ``jnp.maximum.accumulate`` incident), serial loop primitives
  in the kernel layers (the PR 3 rolling pathology), host-sync calls in
  device-hot modules, unpaired ``start_trace``-style acquisitions (the
  PR 2 bug), and raw ``jnp.mean``/``jnp.std`` where ``ops.masked``
  reductions are mandated.
* **Tier B** (:mod:`.jaxpr_tier`) — abstract-traces every registered
  kernel at the canonical ``(days, tickers, 240)`` shape and walks the
  closed jaxpr: zero ``while``/``scan`` primitives, zero f64
  ``convert_element_type``, zero host callbacks, plus a per-kernel
  primitive-count fingerprint written to ``analysis_report.json`` so
  graph drift is diffable in review.
* **Tier C** (:mod:`.concurrency_tier`) — lock-discipline and
  thread-lifecycle rules GL-C1..GL-C4 over the threaded layers
  (``serve/``, ``fleet/``, ``stream/``, ``research/``,
  ``telemetry/``), driven by the ``GLC_CONTRACT`` declarations that
  live next to each thread-shared class. Its runtime twin
  (:mod:`..telemetry.lockcheck`, ``MFF_LOCK_ASSERT=1``) asserts the
  same contracts at mutation time.

Accepted violations live in the committed :data:`BASELINE_PATH`
(:mod:`.violations`), each with a mandatory written justification.
Run it: ``python -m replication_of_minute_frequency_factor_tpu analyze``.
"""

from __future__ import annotations

from .violations import BASELINE_PATH, Baseline, Violation
from .ast_tier import run_ast_tier
from .concurrency_tier import run_concurrency_tier
from .jaxpr_tier import run_jaxpr_tier
from .report import build_report, manifest_block, write_report

__all__ = [
    "BASELINE_PATH", "Baseline", "Violation", "build_report",
    "manifest_block", "run_ast_tier", "run_concurrency_tier",
    "run_jaxpr_tier", "write_report",
]
