"""Tier A: the AST rule engine (rules GL-A1..GL-A6).

One parse per file, one ancestor-tracking walk, every rule dispatched
per node. Rules never import the scanned files — only their AST — so
fixture files with deliberate violations are safe to scan. The only
live imports are of *jax itself* (rule GL-A1 resolves attribute chains
against the installed modules, which is the entire point: the linter's
truth is the pinned jax, not a hardcoded API list).

Rule catalog (docs/static-analysis.md):

GL-A1  jax attribute chains that do not exist on the installed jax
       (the ``jnp.maximum.accumulate`` / ``jax.distributed.is_initialized``
       incident class).
GL-A2  serial loop constructs in the kernel layers (``ops/``,
       ``models/``): ``jnp.roll`` inside a loop, or any
       ``lax.fori_loop``/``while_loop``/``scan`` — the pathology the
       fused rolling engine exists to avoid.
GL-A3  host-sync calls in device-hot modules (``ops/``, ``models/``,
       ``parallel/``): ``.item()``, ``.block_until_ready()``,
       ``np.asarray``/``np.array``, ``float()``/``int()`` of a
       jax expression.
GL-A4  unpaired resource acquisition (``start_trace`` without a
       guaranteed ``stop_trace`` via try/finally or an
       ``__enter__``/``__exit__`` pair) — anywhere in the package.
GL-A5  raw ``jnp.mean``/``std``/``var``/``nan*`` reductions in
       ``models/`` where the ``ops.masked`` equivalents are mandated.
GL-A6  a ``@register("x")`` kernel in ``models/`` with no matching
       module-level ``finalize_class("x", <literal>)`` declaration
       (ISSUE 18), or a declaration whose class is not one of the
       three literal exactness classes. The static mirror of
       ``registry.finalize_classes()``'s loud runtime failure — the
       linter catches the gap at review time, the registry at load
       time.
"""

from __future__ import annotations

import ast
import importlib
import os
import types
from typing import Dict, List, Optional, Tuple

from .violations import Violation

#: layers whose modules must stay free of serial loops (GL-A2)
LOOP_SCOPE = ("ops", "models")
#: layers whose modules must stay free of host syncs (GL-A3).
#: ``telemetry`` joined with ISSUE 8: the ops plane's sampler thread
#: reads device memory from host code, and those reads
#: (``.memory_stats()`` / ``jax.live_arrays``) must stay confined to
#: its declared boundary module, not leak into instrumented hot paths.
#: ``fleet`` joined with ISSUE 11: the router sits in front of N
#: replicas' dispatch queues — a sync on the routing path would stall
#: the whole pod, so the layer keeps the full rule with two declared
#: boundary modules (below).
#: ``research`` joined with ISSUE 14: the discovery loop's whole
#: contract is ONE host-blocking sync per generation — any stray sync
#: in the layer silently doubles the budget — so the layer keeps the
#: full rule with ``research/evolve.py`` as its declared boundary
#: (the per-generation fitness fetch).
HOST_SYNC_SCOPE = ("ops", "models", "parallel", "serve", "stream",
                   "telemetry", "fleet", "research")
#: module-granular GL-A3 extensions (ISSUE 10): ``data/`` as a layer is
#: host-side by design (the ingest encoder and the parquet IO live
#: there), but ``data/result_wire.py`` is device-hot — its encode fuses
#: into every producing graph, and its host decode must operate on an
#: ALREADY-FETCHED buffer, never trigger the fetch itself. Scoping the
#: module keeps any ``np.asarray``/``.item()`` sync from creeping into
#: it; the fetch stays the caller's declared boundary. ISSUE 20 pins
#: the evented front door the same way: ``serve/edge.py`` is a
#: single-threaded event loop — ONE stray sync stalls every
#: multiplexed connection at once — and ``serve/wireclient.py``
#: decodes host bytes a socket read already fetched. Both ride the
#: serve layer scope today, but the module pins keep them in scope
#: regardless of layer-tuple edits, and NEITHER gets a
#: GLA3_BOUNDARY_SYNCS allowance: the serve layer's one declared sync
#: stays in serve/service.py, on a worker thread.
HOST_SYNC_MODULES = frozenset({"data/result_wire.py", "serve/edge.py",
                               "serve/wireclient.py"})
#: layer where raw jnp reductions are banned in favour of ops.masked (GL-A5)
MASKED_SCOPE = ("models",)

#: GL-A3 boundary-module policy (docs/static-analysis.md): a device-hot
#: layer's HOST-SIDE boundary modules declare their allowed sync points
#: here, per (package-relative module path -> allowed symbols). This is
#: deliberately NOT a path exclusion: any sync symbol a boundary module
#: uses beyond its listed set still flags, and every other module in
#: the layer keeps the full rule. Three entries: the serving request
#: loop, whose single declared sync is the ``np.asarray`` that
#: materializes a query's answer from the device block
#: (serve/service.py — the serve layer's host/device boundary); the
#: ops-plane watermark sampler (ISSUE 8), whose declared host reads
#: are the device-memory introspection calls its sampler thread makes
#: (telemetry/opsplane.py — the only module allowed to touch
#: ``.memory_stats()`` / ``jax.live_arrays``); and the mesh-plane
#: shard-balance sampler (ISSUE 9), whose declared sync is the
#: per-shard ``.block_until_ready()`` readiness probe its watcher
#: threads run (telemetry/meshplane.py — watermark blocking stays
#: centralized there, never in an instrumented hot path). ISSUE 11
#: adds the fleet layer's two boundaries: the router's single
#: ``np.asarray`` normalizes an ingest body ONCE before the N-replica
#: fan-out (fleet/router.py), and the replica lifecycle's single
#: ``.block_until_ready()`` is the device-liveness probe on the
#: submesh lead (fleet/replica.py) — routing/policy/http modules keep
#: the full rule. ISSUE 12 adds the factor-health plane: its one
#: declared sync is the ``np.asarray`` that materializes the tiny
#: fused ``[F, 9]`` stats side-output (telemetry/factorplane.py) —
#: the stats ride a fetch that already happened, and the
#: materialization stays centralized there, never in an instrumented
#: hot path. ISSUE 14 adds the research layer's one boundary: the
#: evolutionary loop's single ``np.asarray`` materializes one
#: generation's ``[P, 4]`` stats matrix — the ONE labeled
#: host-blocking sync of the generation contract
#: (research/evolve.py); the fitness graph and the genome registry
#: keep the full rule. ISSUE 16 adds the SLO plane's timeline: its
#: one declared sync symbol is the ``np.asarray`` that ranks
#: top-moving series over an alert window (telemetry/timeline.py —
#: host lists only, but the AST tier cannot see dtypes, so the
#: symbol is declared per-module like every other boundary); the
#: sampler itself reads registry snapshots and host mirrors, never a
#: device value.
GLA3_BOUNDARY_SYNCS = {
    "serve/service.py": frozenset({"np.asarray"}),
    "research/evolve.py": frozenset({"np.asarray"}),
    "telemetry/timeline.py": frozenset({"np.asarray"}),
    "telemetry/opsplane.py": frozenset({".memory_stats()",
                                        "jax.live_arrays"}),
    "telemetry/meshplane.py": frozenset({".block_until_ready()"}),
    "telemetry/factorplane.py": frozenset({"np.asarray"}),
    "fleet/router.py": frozenset({"np.asarray"}),
    "fleet/replica.py": frozenset({".block_until_ready()"}),
}

#: (acquire, release) method-name pairs for GL-A4
RESOURCE_PAIRS = (("start_trace", "stop_trace"),)

#: lax serial-loop entry points (GL-A2)
SERIAL_LOOP_CALLS = {"fori_loop", "while_loop", "scan"}

#: raw reductions with mandated ops.masked equivalents (GL-A5)
RAW_REDUCTIONS = {"mean", "std", "var", "average", "median",
                  "nanmean", "nanstd", "nanvar", "nanmedian"}

#: layer whose registered kernels must declare a finalize class (GL-A6)
FINALIZE_SCOPE = ("models",)
#: the three exactness classes (GL-A6) — the static mirror of
#: ``models.registry.FINALIZE_CLASS_VALUES`` (the rule never imports
#: the scanned package, so the literal set is pinned here; the
#: registry's own ValueError guards runtime drift between the two)
FINALIZE_CLASS_LITERALS = ("exact_fold", "stat_fold", "batch_only")


# --------------------------------------------------------------------------
# import-alias and attribute-chain helpers
# --------------------------------------------------------------------------


def _collect_imports(tree: ast.AST) -> Dict[str, str]:
    """Local name -> dotted path, for names bound from jax/numpy."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                root = a.name.split(".")[0]
                if root not in ("jax", "numpy"):
                    continue
                if a.asname:
                    out[a.asname] = a.name
                else:
                    out[root] = root
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            if node.module and node.module.split(".")[0] in ("jax",
                                                            "numpy"):
                for a in node.names:
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def _attr_chain(node: ast.Attribute) -> Tuple[Optional[str], List[str]]:
    """``a.b.c`` -> ('a', ['b', 'c']); None root if not Name-rooted."""
    parts: List[str] = []
    cur: ast.AST = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        return cur.id, list(reversed(parts))
    return None, []


_import_cache: Dict[str, Optional[object]] = {}


def _import_dotted(dotted: str) -> Optional[object]:
    if dotted in _import_cache:
        return _import_cache[dotted]
    obj: Optional[object]
    try:
        obj = importlib.import_module(dotted)
    except ImportError:
        obj = None
        if "." in dotted:
            head, _, tail = dotted.rpartition(".")
            base = _import_dotted(head)
            if base is not None:
                obj = getattr(base, tail, None)
    _import_cache[dotted] = obj
    return obj


_chain_cache: Dict[Tuple[str, Tuple[str, ...]], int] = {}


def _chain_failure(dotted_root: str, attrs: Tuple[str, ...]) -> int:
    """Index of the first attr that does not resolve on the live
    modules, or -1 when the whole chain (or the root itself) resolves
    /cannot be checked."""
    key = (dotted_root, attrs)
    if key in _chain_cache:
        return _chain_cache[key]
    obj = _import_dotted(dotted_root)
    result = -1
    if obj is not None:
        for i, a in enumerate(attrs):
            try:
                obj = getattr(obj, a)
            except AttributeError:
                # a submodule may simply not be imported yet
                if isinstance(obj, types.ModuleType):
                    try:
                        obj = importlib.import_module(
                            f"{obj.__name__}.{a}")
                        continue
                    except ImportError:
                        pass
                result = i
                break
    _chain_cache[key] = result
    return result


def _dotted_of(scan: "_ModuleScan", name: str) -> Optional[str]:
    return scan.imports.get(name)


def _is_jax_rooted(scan: "_ModuleScan", node: ast.AST) -> bool:
    """Does ``node``'s subtree reference any jax-bound name?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            dotted = scan.imports.get(sub.id)
            if dotted and dotted.split(".")[0] == "jax":
                return True
    return False


def _call_target(scan: "_ModuleScan", call: ast.Call
                 ) -> Tuple[Optional[str], str]:
    """(dotted module path or None, final attr/function name)."""
    f = call.func
    if isinstance(f, ast.Attribute):
        root, attrs = _attr_chain(f)
        if root is not None:
            dotted = _dotted_of(scan, root)
            if dotted is not None:
                return ".".join([dotted] + attrs[:-1]), attrs[-1]
        return None, f.attr
    if isinstance(f, ast.Name):
        dotted = _dotted_of(scan, f.id)
        if dotted is not None:
            head, _, tail = dotted.rpartition(".")
            return head, tail
        return None, f.id
    return None, ""


# --------------------------------------------------------------------------
# per-module scan
# --------------------------------------------------------------------------


class _ModuleScan:
    def __init__(self, file_path: str, display_path: str,
                 scope_parts: Tuple[str, ...]):
        self.file_path = file_path
        self.path = display_path
        self.scope_parts = scope_parts
        with open(file_path, "rb") as fh:
            self.tree = ast.parse(fh.read(), filename=file_path)
        self.imports = _collect_imports(self.tree)
        self.violations: List[Violation] = []

    def in_scope(self, layers: Tuple[str, ...]) -> bool:
        return bool(set(self.scope_parts[:-1]) & set(layers))

    def add(self, code: str, node: ast.AST, symbol: str,
            message: str) -> None:
        self.violations.append(Violation(
            code=code, path=self.path,
            line=getattr(node, "lineno", 0), symbol=symbol,
            message=message))


def _rule_a1(scan: _ModuleScan, node: ast.AST,
             stack: List[ast.AST]) -> None:
    """GL-A1: jax attribute chains missing on the installed jax."""
    if not isinstance(node, ast.Attribute):
        return
    if stack and isinstance(stack[-1], ast.Attribute):
        return  # only maximal chains
    root, attrs = _attr_chain(node)
    if root is None:
        return
    dotted = _dotted_of(scan, root)
    if dotted is None or dotted.split(".")[0] != "jax":
        return
    i = _chain_failure(dotted, tuple(attrs))
    if i >= 0:
        symbol = ".".join([root] + attrs[:i + 1])
        resolved = ".".join([dotted] + attrs[:i + 1])
        scan.add("GL-A1", node, symbol,
                 f"{resolved} does not exist on the installed jax "
                 "(the jnp.maximum.accumulate incident class); use an "
                 "API present on the pinned version")


def _rule_a2(scan: _ModuleScan, node: ast.AST,
             stack: List[ast.AST]) -> None:
    """GL-A2: serial loop constructs in ops/ and models/."""
    if not scan.in_scope(LOOP_SCOPE) or not isinstance(node, ast.Call):
        return
    dotted, name = _call_target(scan, node)
    if name == "roll" and dotted in ("jax.numpy", "numpy"):
        if any(isinstance(a, (ast.For, ast.While)) for a in stack):
            scan.add("GL-A2", node, f"{name} in loop",
                     "full-tensor roll inside a loop builds a serial "
                     "dependency chain (the pre-PR-3 rolling-moment "
                     "pathology); materialize windows by strided "
                     "gather instead (ops/rolling.py)")
        return
    if name in SERIAL_LOOP_CALLS and dotted == "jax.lax":
        scan.add("GL-A2", node, name,
                 f"lax.{name} in a kernel-layer module serializes the "
                 "graph into an XLA while; express the computation as "
                 "an unrolled/batched formulation")


def _a3_add(scan: _ModuleScan, node: ast.AST, symbol: str,
            msg: str) -> None:
    """Record a GL-A3 hit unless the module's boundary policy allows
    exactly this symbol (GLA3_BOUNDARY_SYNCS — per-symbol, never a
    blanket module exclusion)."""
    allowed = GLA3_BOUNDARY_SYNCS.get("/".join(scan.scope_parts), ())
    if symbol in allowed:
        return
    scan.add("GL-A3", node, symbol, msg)


def _rule_a3(scan: _ModuleScan, node: ast.AST,
             stack: List[ast.AST]) -> None:
    """GL-A3: host-sync calls in device-hot modules."""
    in_scope = (scan.in_scope(HOST_SYNC_SCOPE)
                or "/".join(scan.scope_parts) in HOST_SYNC_MODULES)
    if not in_scope or not isinstance(node, ast.Call):
        return
    msg = ("host-device synchronization in a device-hot module blocks "
           "the dispatch pipeline; move it to a bench/telemetry/CLI "
           "layer or fetch explicitly via jax.device_get there")
    mem_msg = ("device-memory introspection is a host read of backend "
               "state; route it through telemetry.opsplane.HbmSampler "
               "(the declared boundary module) so rate limiting and "
               "graceful degradation are centralized")
    if isinstance(node.func, ast.Attribute):
        if node.func.attr == "item" and not node.args:
            _a3_add(scan, node, ".item()", msg)
            return
        if node.func.attr == "block_until_ready":
            _a3_add(scan, node, ".block_until_ready()", msg)
            return
        # ISSUE 8: device-memory host reads are boundary-module-only
        if node.func.attr in ("memory_stats", "live_buffers") \
                and not node.args:
            _a3_add(scan, node, f".{node.func.attr}()", mem_msg)
            return
    dotted, name = _call_target(scan, node)
    if dotted == "numpy" and name in ("asarray", "array"):
        _a3_add(scan, node, f"np.{name}", msg)
        return
    if dotted == "jax" and name == "live_arrays":
        _a3_add(scan, node, "jax.live_arrays", mem_msg)
        return
    if (isinstance(node.func, ast.Name) and node.func.id in ("float",
                                                             "int")
            and len(node.args) == 1
            and _is_jax_rooted(scan, node.args[0])):
        _a3_add(scan, node, f"{node.func.id}(jax expression)", msg)


def _contains_call_named(nodes, names) -> bool:
    for n in nodes if isinstance(nodes, list) else [nodes]:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Call):
                f = sub.func
                if (isinstance(f, ast.Attribute) and f.attr in names) or \
                        (isinstance(f, ast.Name) and f.id in names):
                    return True
    return False


def _rule_a4(scan: _ModuleScan, node: ast.AST,
             stack: List[ast.AST]) -> None:
    """GL-A4: resource acquisitions without a guaranteed release."""
    if not isinstance(node, ast.Call):
        return
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else "")
    for acquire, release in RESOURCE_PAIRS:
        if name != acquire:
            continue
        func = next((n for n in reversed(stack)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))), None)
        container: ast.AST = func if func is not None else scan.tree
        ok = False
        for t in ast.walk(container):
            if not isinstance(t, ast.Try) or not t.finalbody:
                continue
            if not _contains_call_named(t.finalbody, {release}):
                continue
            # guaranteed iff the acquire either runs inside the try
            # (stack contains it) or strictly before it in the same
            # function — both reach the finally on every exit path
            if t in stack or node.lineno < t.lineno:
                ok = True
                break
        if not ok and func is not None and func.name == "__enter__":
            cls = next((n for n in reversed(stack)
                        if isinstance(n, ast.ClassDef)), None)
            if cls is not None:
                exits = [m for m in cls.body
                         if isinstance(m, ast.FunctionDef)
                         and m.name == "__exit__"]
                if exits and _contains_call_named(exits, {release}):
                    ok = True
        if not ok:
            scan.add("GL-A4", node, acquire,
                     f"{acquire} without a guaranteed {release} (the "
                     "PR 2 unpaired-start_trace bug class): wrap in "
                     "try/finally, or pair __enter__ with an __exit__ "
                     "that releases")


def _rule_a5(scan: _ModuleScan, node: ast.AST,
             stack: List[ast.AST]) -> None:
    """GL-A5: raw jnp reductions in models/ (ops.masked is mandated)."""
    if not scan.in_scope(MASKED_SCOPE) or not isinstance(node, ast.Call):
        return
    dotted, name = _call_target(scan, node)
    if dotted == "jax.numpy" and name in RAW_REDUCTIONS:
        scan.add("GL-A5", node, f"jnp.{name}",
                 f"raw jnp.{name} ignores the present-bar mask; "
                 "models/ must use the ops.masked equivalent "
                 "(masked_mean/masked_std/...) so missing bars match "
                 "polars null semantics")


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _str_names(node: ast.AST, env: Dict[str, Tuple[str, ...]]
               ) -> Optional[Tuple[str, ...]]:
    """Statically resolve a kernel-name argument: a str constant
    directly, or a ``for``-loop variable bound (in ``env``) to a
    literal tuple/list of str constants. None = unresolvable."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, ast.Name) and node.id in env:
        return env[node.id]
    return None


def _rule_a6_module(scan: _ModuleScan) -> None:
    """GL-A6: every ``@register("x")`` kernel in models/ declares a
    matching module-level ``finalize_class("x", <literal>)``.

    Both declaration idioms in the family modules resolve statically:
    a direct str-literal call, and the ``for _n in (<str literals>,):``
    loop form. The walk threads a loop-variable environment so the
    loop form counts; anything the rule cannot resolve (a computed
    name, a non-literal class) flags rather than silently passing —
    the registry's runtime check is the backstop, the linter is the
    review-time gate."""
    if not scan.in_scope(FINALIZE_SCOPE):
        return
    registered: Dict[str, ast.AST] = {}
    declared: set = set()

    def visit(node: ast.AST, env: Dict[str, Tuple[str, ...]]) -> None:
        if isinstance(node, ast.For) and isinstance(node.target,
                                                    ast.Name):
            try:
                vals = ast.literal_eval(node.iter)
            except (ValueError, SyntaxError):
                vals = None
            if isinstance(vals, (tuple, list)) and all(
                    isinstance(v, str) for v in vals):
                env = {**env, node.target.id: tuple(vals)}
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) \
                        and _call_name(dec) == "register" and dec.args:
                    names = _str_names(dec.args[0], env)
                    if names:
                        for n in names:
                            registered[n] = dec
        if isinstance(node, ast.Call) \
                and _call_name(node) == "finalize_class":
            names = _str_names(node.args[0], env) if node.args else None
            if names is None:
                scan.add("GL-A6", node, "finalize_class(<dynamic>)",
                         "finalize_class with a statically "
                         "unresolvable kernel name: declare with a "
                         "str literal or a literal-tuple for-loop so "
                         "the linter can match it to @register")
            else:
                declared.update(names)
            cls = node.args[1] if len(node.args) > 1 else None
            if not (isinstance(cls, ast.Constant)
                    and cls.value in FINALIZE_CLASS_LITERALS):
                scan.add("GL-A6", node, "finalize_class(..., <class>)",
                         "finalize class must be one of the literal "
                         f"exactness classes {FINALIZE_CLASS_LITERALS}"
                         " (docs/streaming.md 'Exactness classes')")
        for child in ast.iter_child_nodes(node):
            visit(child, env)

    visit(scan.tree, {})
    for name, node in sorted(registered.items()):
        if name not in declared:
            scan.add("GL-A6", node, f"register({name!r})",
                     f"registered kernel {name!r} declares no "
                     "finalize_class: every kernel must pick "
                     "exact_fold / stat_fold / batch_only (ISSUE 18) "
                     "or the fast-finalize partition silently "
                     "misroutes it — fails loudly at load via "
                     "registry.finalize_classes(), and here at "
                     "review time")


_RULES = (_rule_a1, _rule_a2, _rule_a3, _rule_a4, _rule_a5)


def _walk(node: ast.AST, stack: List[ast.AST], scan: _ModuleScan) -> None:
    for rule in _RULES:
        rule(scan, node, stack)
    stack.append(node)
    for child in ast.iter_child_nodes(node):
        _walk(child, stack, scan)
    stack.pop()


def scan_file(file_path: str, display_path: str,
              scope_rel: str) -> List[Violation]:
    parts = tuple(scope_rel.replace(os.sep, "/").split("/"))
    scan = _ModuleScan(file_path, display_path, parts)
    _walk(scan.tree, [], scan)
    _rule_a6_module(scan)
    return scan.violations


def run_ast_tier(root: Optional[str] = None,
                 display_base: Optional[str] = None
                 ) -> Tuple[List[Violation], int]:
    """Scan every ``.py`` under ``root`` (default: this package).

    ``display_base`` anchors the repo-relative paths recorded on
    violations (default: the package's parent, i.e. the repo root for
    a source checkout). Returns (violations, files_scanned).
    """
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if display_base is None:
        display_base = os.path.dirname(root)
    files = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__",))
        files += [os.path.join(dirpath, f) for f in sorted(filenames)
                  if f.endswith(".py")]
    out: List[Violation] = []
    for f in files:
        display = os.path.relpath(f, display_base).replace(os.sep, "/")
        scope = os.path.relpath(f, root)
        out += scan_file(f, display, scope)
    return out, len(files)
