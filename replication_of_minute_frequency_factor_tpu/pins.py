"""Repo-wide readings for the two polars semantics that cannot be
verified in this container (no polars wheel, no network — VERDICT r2).

Each pin names a behavior of the reference's engine that its expression
text does not determine and that no environment here can observe. The
repo implements BOTH readings of each and defaults to the one argued in
``tools/refdiff/polars_shim.SEMANTIC_PINS``; ``tests/test_pin_bounds.py``
runs the full reference differential under each reading and records the
exact blast radius, so a wrong default is a one-line flip HERE — this
dict is the single registry: the shim, the numpy oracle, and the
production JAX kernels (ops/masked.py, ops/rolling.py,
eval_ops.qcut_labels) all consult it — not a silent correctness hole.

Pins:

``constant_window`` — whether a constant window (limit-locked stock)
produces exactly-zero variance (``"degenerate"``, default: moments run
on first-observation-anchored series) or two-pass f64 rounding noise
(``"noise"``). Decides which branch the reference's
``when(var_x*var_y != 0)`` guards take
(/root/reference/MinuteFrequentFactorCalculateMethodsCICC.py:130-141).

``qcut_nan`` — whether group_test's qcut buckets a value-NaN exposure to
null (``"exclude"``, default) or to the top bin under polars' total
float order (``"top_bin"``). The reference's group_test never filters
NaN exposures (/root/reference/Factor.py:280-292), so this decides
whether NaN-exposure stocks silently join the best-factor bucket.
"""

from __future__ import annotations

READINGS = {
    "constant_window": "degenerate",  # or "noise"
    "qcut_nan": "exclude",            # or "top_bin"
}

_VALID = {
    "constant_window": ("degenerate", "noise"),
    "qcut_nan": ("exclude", "top_bin"),
}


def reading(name: str) -> str:
    return READINGS[name]


def _clear_traces():
    """The JAX kernels consult READINGS at trace time (ops/masked.py,
    ops/rolling.py, eval_ops.qcut_labels), so a flip must invalidate
    cached traces. Only needed if jax is already loaded."""
    import sys

    jax = sys.modules.get("jax")
    if jax is not None:
        jax.clear_caches()


class pinned:
    """Context manager: temporarily select alternative readings.

    ``with pins.pinned(constant_window="noise"): ...``

    Entering/exiting with an actual change clears JAX's jit caches —
    the production kernels bake the reading in at trace time.
    """

    def __init__(self, **overrides):
        for k, v in overrides.items():
            if v not in _VALID[k]:
                raise ValueError(f"{k}: unknown reading {v!r}")
        self._overrides = overrides

    def __enter__(self):
        self._saved = {k: READINGS[k] for k in self._overrides}
        READINGS.update(self._overrides)
        if self._saved != dict(self._overrides):
            _clear_traces()
        return self

    def __exit__(self, *exc):
        changed = {k: READINGS[k] for k in self._saved} != self._saved
        READINGS.update(self._saved)
        if changed:
            _clear_traces()
        return False
